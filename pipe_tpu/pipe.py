"""The user API: ``Pipe`` — wrap a Sequential, train it pipelined.

Capability parity with reference ``Pipe`` (``pipe.py:224-494``):

* constructor ``Pipe(module, chunks, checkpoint, ...)`` with the same fail-fast
  validation (``pipe.py:324-345``);
* container protocol ``__len__``/``__getitem__``/``__iter__`` over stages
  (``pipe.py:358-386``);
* ``forward`` = check → scatter → run schedule → gather (``pipe.py:431-494``);
* ``NoChunk`` passthrough for non-batch inputs (``pipe.py:462-464``).

Deliberate re-idiomizations (documented, not ported):

* Stage placement is a stage count / ``balance`` list, not device tags —
  ``_retrieve_device``'s cut-at-device-change (``pipe.py:94-118``) has no TPU
  meaning; the mesh owns placement. ``WithDevice`` is therefore not carried.
* ``MOVING_DENIED`` (``pipe.py:388-415``) is moot: params are immutable pytrees;
  there is no ``.cuda()``/``.to()`` to deny.
* The RPC/RRef layer is vestigial in the reference (disabled with zero effect,
  ``pipe.py:318-323,491-494``; ``README.md:545``) and is not carried; multi-host
  is JAX's single-controller runtime.
* ``forward`` is pure: ``out = pipe(params, x, key=..., train=...)``.
"""

from __future__ import annotations

import warnings
from typing import Any, List, Optional, Sequence

import jax
from jax.sharding import Mesh

from .core import microbatch as mb
from .core.partition import (Stage, StageCtx, split_balance, verify_splitting,
                             verify_stages)
from .core.remat import validate_mode
from .core.schedule import GPipeSchedule, Schedule, get_schedule
from .ops.layers import Module, Sequential
from .parallel import emulator

__all__ = ["Pipe", "NoChunk", "BalanceError"]

NoChunk = mb.NoChunk
from .core.partition import BalanceError  # re-export (API parity)


class Pipe:
    """Synchronous GPipe pipeline over a Sequential of stages.

    Unlike the reference's stateful ``nn.Module`` wrapper, ``Pipe`` is a pure
    program: ``init`` returns per-stage params, ``__call__`` maps
    ``(params, *inputs)`` to outputs. Executor selection:

    * no mesh (default): serial clock-cycle emulator, any stage shapes;
    * ``mesh=``: compiled SPMD executor over the mesh's ``stage`` axis —
      heterogeneous partitions via ``lax.switch`` stage bodies, uneven
      balance, ``@skippable`` lanes, optional ``data`` axis (see
      ``pipe_tpu.parallel.hetero``). Homogeneous stage-stacked models at
      memory scale use ``pipe_tpu.parallel.spmd`` / ``.scheduled`` directly.
    """

    def __init__(self,
                 module: Sequential,
                 chunks: int = 1,
                 checkpoint: str = "except_last",
                 *,
                 mesh: Optional[Mesh] = None,
                 n_stages: Optional[int] = None,
                 balance: Optional[Sequence[int]] = None,
                 schedule: str = "gpipe",
                 plan=None,
                 deferred_batch_norm: bool = False,
                 remat_policy=None,
                 overlap_transport: Optional[bool] = None,
                 phase_compile: Optional[bool] = None):
        # --- auto-planner front door (core/planner.py): a Plan (or a path
        # to a saved PLAN json) fixes chunks/schedule/balance/n_stages and
        # the checkpoint mode it was scored under — the one-liner the
        # planner exists for. Config the plan already decides cannot also
        # be hand-passed (conflicting sources would silently disagree).
        if plan is not None:
            from .core.planner import Plan
            if isinstance(plan, str):
                plan = Plan.load(plan)
            if (chunks != 1 or balance is not None or n_stages is not None
                    or schedule != "gpipe"):
                raise ValueError(
                    "Pipe(plan=...) already fixes chunks, schedule, "
                    "balance and n_stages — drop the hand-passed values "
                    "(or drop the plan)")
            if checkpoint != "except_last" \
                    and checkpoint != plan.checkpoint:
                raise ValueError(
                    f"checkpoint={checkpoint!r} conflicts with the plan's "
                    f"{plan.checkpoint!r} (the plan was scored under its "
                    f"own checkpoint mode)")
            chunks = plan.m
            checkpoint = plan.checkpoint
            schedule = plan.schedule_obj()
            balance = list(plan.balance)
            n_stages = len(balance)
            if plan.split_stage:
                warnings.warn(
                    "this plan prescribes the structural B/W split "
                    "(split_stage=True); the Pipe front door's "
                    "heterogeneous executor runs split-backward tables "
                    "via the stored-vjp path instead — drive "
                    "ScheduledPipeline (or Trainer) with the plan to "
                    "engage the split", stacklevel=2)
        self.plan = plan
        # --- fail-fast validation (reference pipe.py:324-345) ---
        if not isinstance(chunks, int) or isinstance(chunks, bool):
            raise TypeError("chunks must be an integer")
        if chunks <= 0:
            raise ValueError("number of chunks must be positive")
        validate_mode(checkpoint)
        if not isinstance(module, Sequential):
            raise TypeError("module must be a pipe_tpu Sequential")
        seen = set()
        for layer in module:
            if id(layer) in seen:
                raise ValueError("module with duplicate children is not supported")
            seen.add(id(layer))

        self.chunks = chunks
        self.checkpoint = checkpoint
        self.module = module
        # Selective remat policy (e.g. jax.checkpoint_policies.dots_saveable)
        # for the RECOMPUTE micro-batches — flows to the training executor;
        # the forward path takes it per-call (and falls back to this).
        self.remat_policy = remat_policy
        # Overlapped (software-pipelined, packed) boundary transport for
        # the training executor — tri-state, resolved per backend; see
        # ScheduledPipeline.overlap_transport.
        self.overlap_transport = overlap_transport
        # Phase-compiled lowering of the op tables (warmup/cooldown
        # unrolled, steady state a switch-free lax.scan) — tri-state like
        # overlap_transport; see ScheduledPipeline.phase_compile.
        self.phase_compile = phase_compile

        if deferred_batch_norm:
            from .extras.norm import convert_deferred_batch_norm
            module = convert_deferred_batch_norm(module, chunks)
            self.module = module
        self.deferred_batch_norm = deferred_batch_norm

        if balance is not None and n_stages is None:
            n_stages = len(balance)
        sched_obj = (get_schedule(schedule) if isinstance(schedule, str)
                     else schedule)
        if mesh is not None:
            from .parallel.mesh import STAGE_AXIS
            if STAGE_AXIS not in mesh.axis_names:
                raise ValueError(
                    f"mesh must have a {STAGE_AXIS!r} axis to drive a Pipe")
            mesh_stages = mesh.shape[STAGE_AXIS]
            # Interleaved schedules host v virtual stages per device: the
            # module splits into v*d partitions, virtual stage s on device
            # s % d. Non-interleaved (v == 1): one partition per device.
            expected = mesh_stages * sched_obj.v
            if n_stages is None:
                n_stages = expected
            elif n_stages != expected:
                raise ValueError(
                    f"n_stages={n_stages} does not match the mesh's "
                    f"{mesh_stages}-device stage axis for schedule "
                    f"{sched_obj.name!r} (needs v*d = {expected})")
            if deferred_batch_norm and getattr(sched_obj, "splits_backward",
                                               False):
                raise NotImplementedError(
                    "deferred_batch_norm does not compose with "
                    "split-backward schedules (zb-h1): the W op's vjp seed "
                    "has no stats slot — pick 'gpipe' or '1f1b'")
        if n_stages is None:
            n_stages = 1
        self.balance = split_balance(len(module), n_stages, balance)
        self.n_stages = n_stages
        self.mesh = mesh

        # Partition the Sequential into per-stage sub-Sequentials
        # (reference _split_module/_assemble_partition, pipe.py:181-218).
        self.partitions: List[Sequential] = []
        offset = 0
        for width in self.balance:
            self.partitions.append(module[offset:offset + width])
            offset += width

        self.stages: List[Stage] = [
            Stage(part.apply, name=f"stage{j}")
            for j, part in enumerate(self.partitions)
        ]
        verify_stages(self.stages)
        self._schedule: Schedule = sched_obj

        # Skip-connection wiring: fail-fast verification at init (reference
        # verify_skippables, pipe.py:336) and the static stash->pop layout
        # (reference inspect_skip_layout, pipe.py:348).
        from .extras.skip import inspect_skip_layout, verify_skippables
        verify_skippables(self.module)
        self.skip_layout = inspect_skip_layout(self.partitions)
        # After verify_skippables, every declared stash/pop resolves to a
        # layout pair, so this single flag decides tracker creation.
        self._needs_skip_tracker = self.skip_layout.num_skips > 0

        # mesh= selects the compiled SPMD executors (the reference's flagship
        # multi-device product: Pipe.__init__ builds the multi-device
        # Pipeline, pipe.py:344-356; forward runs it, pipe.py:431-494):
        # * forward (`__call__`): the GPipe-wavefront hetero executor —
        #   forward has no backward to interleave, so every schedule's
        #   forward IS the wavefront (v == 1); interleaved placements
        #   (v > 1) run the op tables with BWD rows masked to IDLE via
        #   the table executor's forward() (reference eval-mode pipeline,
        #   pipeline.py:153-155);
        # * training (`loss_and_grad`): the schedule-table executor, giving
        #   1F1B's min(m, n) activation cap, zb-h1, interleaved-1f1b and the
        #   exact per-micro-batch checkpoint policy through the flagship API.
        self._executor = None
        self._train_executor = None
        if mesh is not None:
            if sched_obj.v == 1:
                from .parallel.hetero import HeteroSpmdPipeline
                self._executor = HeteroSpmdPipeline(
                    mesh, self.partitions, self.skip_layout, chunks,
                    checkpoint)
            # every combination that reaches here has a train path (the
            # sole BN exclusion left is zb-h1, raised above; BN x v>1
            # rides the table executor's stat lanes)
            from .parallel.hetero_scheduled import HeteroScheduledPipeline
            self._train_executor = HeteroScheduledPipeline(
                mesh, self.partitions, self.skip_layout, chunks,
                checkpoint, sched_obj, remat_policy=remat_policy,
                overlap_transport=overlap_transport,
                phase_compile=phase_compile)

    # --- container protocol (reference pipe.py:358-386) ---

    def __len__(self) -> int:
        """Total number of layers across all partitions."""
        return sum(len(p) for p in self.partitions)

    def __getitem__(self, index: int) -> Module:
        layers: List[Module] = []
        for p in self.partitions:
            layers.extend(p)
        return layers[index]

    def __iter__(self):
        for p in self.partitions:
            yield from p

    # --- params ---

    def init(self, key: jax.Array, *example_inputs,
             _host: bool = False) -> List[Any]:
        """Per-stage parameter pytrees, shapes chained stage to stage.

        ``_host=True`` (used by :meth:`init_sharded`) moves each stage's
        fresh params to host numpy immediately, so peak device memory during
        init is ONE stage, not the whole model."""
        import contextlib

        import numpy as np

        # Shape inference through skip-carrying layers: a spec-mode tracker
        # records stash shapes and serves pops as zeros (tracers cannot cross
        # the per-partition eval_shape boundaries).
        cm = contextlib.nullcontext()
        if self._needs_skip_tracker:
            from .extras.skip import SkipTracker, use_skip_tracker
            cm = use_skip_tracker(SkipTracker(self.skip_layout,
                                              spec_mode=True))
        params: List[Any] = []
        specs = [jax.ShapeDtypeStruct(jax.numpy.shape(x), jax.numpy.result_type(x))
                 for x in example_inputs]
        with cm:
            for j, part in enumerate(self.partitions):
                pkey = jax.random.fold_in(key, j)
                p = part.init(pkey, *specs)
                out = part.out_spec(p, *specs)
                if _host:
                    p = jax.tree_util.tree_map(np.asarray, p)
                params.append(p)
                specs = list(out) if isinstance(out, (tuple, list)) else [out]
        verify_splitting(params)
        return params

    # --- stage-sharded params (reference _split_module's partition-per-
    # device placement, pipe.py:191-218,344-356) ---

    def shard_params(self, params: Sequence[Any]):
        """Per-stage trees → stage-sharded packed layout: each device holds
        ONLY its own partition's weights (``{dtype: [n_stages, cap]}`` rows
        sharded over the mesh's stage axis). Requires ``mesh=``. The packed
        dict is a plain pytree — differentiate with respect to it, feed it
        to optax — and :meth:`unshard_params` converts either params or
        grads back to per-stage trees."""
        if self._train_executor is not None:
            # Row order follows the schedule's placement (device-major when
            # interleaved); identical to partition order at v == 1, so the
            # forward executor shares the same pack.
            packed = self._train_executor.shard_params(params)
            if self._executor is not None:
                self._executor.param_pack = self._train_executor.param_pack
            return packed
        if self._executor is None:
            raise ValueError("shard_params requires Pipe(mesh=...)")
        return self._executor.shard_params(params)

    def unshard_params(self, packed):
        if self._train_executor is not None:
            return self._train_executor.unshard_params(packed)
        if self._executor is None:
            raise ValueError("unshard_params requires Pipe(mesh=...)")
        return self._executor.unshard_params(packed)

    unshard_grads = unshard_params

    def init_sharded(self, key: jax.Array, *example_inputs):
        """Initialize straight into the stage-sharded layout. Each stage's
        fresh params move to host before the next stage initializes, and
        sharding builds per-device rows directly — peak device memory is one
        stage's weights, never the whole model."""
        return self.shard_params(
            self.init(key, *example_inputs, _host=True))

    # --- training through the schedule tables (the capability the
    # reference's fork/join machinery exists for, pipeline.py:128-132) ---

    def loss_and_grad(self, params, *inputs, targets: Any = None,
                      loss_fn, key: Optional[jax.Array] = None):
        """One pipelined training step through the configured schedule:
        ``(loss, packed_grads)``, with 1F1B/zb-h1/interleaved memory caps
        and the exact per-micro-batch checkpoint policy. ``params`` must be
        the stage-sharded packed layout (:meth:`shard_params`);
        ``loss_fn(*outputs, targets_mb) -> [rows]`` is the per-row loss.
        Works for every schedule incl. ``gpipe`` (which thereby gains the
        exact ``except_last`` policy the AD wavefront executor approximates
        statically).

        With ``deferred_batch_norm=True`` the return is
        ``(loss, packed_grads, new_params)``: the table executor's stat
        lanes accumulate one mini-batch of BN statistics and the commit
        hands back the refreshed params — mirroring the forward path's
        ``(out, new_params)`` contract."""
        if self._train_executor is None:
            raise ValueError("loss_and_grad requires Pipe(mesh=...)")
        res = self._train_executor.loss_and_grad(
            params, *inputs, targets=targets, loss_fn=loss_fn, key=key)
        if getattr(self._train_executor, "has_bn", False):
            # Deferred-BN: the table executor's stat lanes accumulated one
            # mini-batch of statistics; commit them once (reference
            # batchnorm.py semantics) and hand back the refreshed params —
            # (loss, grads, new_params), mirroring the forward path's
            # (out, new_params) contract.
            loss, grads, stats = res
            return loss, grads, self._commit_bn_mesh(params, stats)
        return res

    def memory_plan(self, chunks: Optional[int] = None) -> dict:
        """Static per-device buffer counts of the training executor — the
        activation-memory story (1F1B: ``min(m, n)`` stashed inputs),
        inspectable from the flagship API."""
        if self._train_executor is None:
            raise ValueError("memory_plan requires a mesh= training path")
        return self._train_executor.memory_plan(chunks)

    # --- forward (reference pipe.py:431-494) ---

    def __call__(self, params: Sequence[Any], *inputs,
                 key: Optional[jax.Array] = None,
                 train: bool = False,
                 remat_policy=None):
        from .extras.norm import DeferredBatchNorm, commit_batchnorm_stats

        explicit_policy = remat_policy
        if remat_policy is None:
            remat_policy = self.remat_policy
        if self._executor is not None:
            res = self._executor(params, *inputs, key=key, train=train,
                                 remat_policy=remat_policy)
            if self._executor.has_bn and train:
                out, stats = res
                return out, self._commit_bn_mesh(params, stats)
            return res
        if self.mesh is not None:
            # interleaved (v > 1) placements: run the op tables with BWD
            # rows masked to IDLE — the reference's eval-mode pipeline with
            # checkpointing off (pipeline.py:153-155). This path has no
            # remat wrapping: eval has no backward, and training goes
            # through loss_and_grad (which owns the checkpoint policy) —
            # refuse an explicit per-call policy rather than ignore it.
            if explicit_policy is not None:
                raise NotImplementedError(
                    "the interleaved (v > 1) forward executor does not "
                    "apply remat_policy — differentiate via loss_and_grad "
                    "(the training path owns checkpointing)")
            res = self._train_executor.forward(params, *inputs, key=key,
                                               train=train)
            if self._train_executor.has_bn and train:
                out, stats = res
                return out, self._commit_bn_mesh(params, stats)
            return res
        if isinstance(params, dict):
            raise TypeError(
                "stage-sharded packed params need Pipe(mesh=...); the serial "
                "emulator takes per-stage trees (use unshard_params)")
        mb.check(*inputs)
        batches = mb.scatter(inputs, self.chunks)
        has_bn = any(isinstance(l, DeferredBatchNorm) for l in self)
        skip_tracker = None
        if has_bn or self._needs_skip_tracker:
            from .extras.skip import SkipTracker
            skip_tracker = SkipTracker(self.skip_layout)
        batches = emulator.run(
            self.stages, list(params), batches,
            schedule=self._schedule,
            checkpoint=self.checkpoint,
            train=train, key=key, remat_policy=remat_policy,
            skip_tracker=skip_tracker)
        out = mb.gather(batches)
        if has_bn and train:
            # Deferred-BN commit: one running-stats update per mini-batch
            # (reference batchnorm.py capability; torch mutates buffers in
            # place, a pure program returns the new params instead).
            new_params = commit_batchnorm_stats(
                self.partitions, list(params), skip_tracker)
            return out, new_params
        return out

    forward = __call__

    def _commit_bn_mesh(self, params, stats: dict):
        """One running-stats momentum update per mini-batch from the
        executor's accumulated stat lanes (reference ``batchnorm.py``
        capability, ``pipe.py:341-342``): pipelined BN running stats equal
        the unpipelined model's. Works on per-stage trees or the packed
        stage-sharded layout (row rebuild via the pack plans); traced ops,
        so it composes with jit."""
        from .extras.norm import (DeferredBatchNorm, _STATS,
                                  commit_batchnorm_stats)

        class _StatsShim:   # tracker-shaped view over the executor's stats
            accum = stats

        if not isinstance(params, dict):
            return commit_batchnorm_stats(self.partitions, list(params),
                                          _StatsShim)
        ex = (self._executor if self._executor is not None
              else self._train_executor)
        pack = ex.param_pack
        new_params = params
        for j, part in enumerate(self.partitions):
            # packed row holding partition j (device-major for interleaved)
            row = ex.row_of(j) if hasattr(ex, "row_of") else j
            tree_j = None
            for i, layer in enumerate(part):
                if not isinstance(layer, DeferredBatchNorm):
                    continue
                st = stats.get((layer.ns, _STATS))
                if st is None:
                    continue
                if tree_j is None:
                    tree_j = pack.unpack_stage(
                        {dt: a[row] for dt, a in params.items()}, row)
                tree_j[i] = layer.commit(tree_j[i], st)
            if tree_j is not None:
                new_params = pack.replace_stage(new_params, row, tree_j)
        return new_params
