"""Runtime: multi-host initialization and global mesh/data placement."""

from .distributed import (global_pipeline_mesh, host_local_batch, initialize,
                          is_initialized, process_summary)

__all__ = ["initialize", "is_initialized", "global_pipeline_mesh",
           "host_local_batch", "process_summary"]
