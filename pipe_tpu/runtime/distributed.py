"""Multi-host runtime: initialize, build global meshes, place host-local data.

The reference's inter-node story is vestigial TensorPipe RPC — disabled with
zero behavioral change (``pipe.py:318-323,491-494``; ``main.py:124-137``;
``README.md:545``: "RPC is useless"). The real TPU-native multi-host plane is
JAX's single-controller runtime: every host runs the same program,
``jax.distributed.initialize`` wires the PJRT processes, meshes span all
hosts' devices (ICI within a slice, DCN across slices), and the compiled
collectives do the rest — no RPC layer to build, which is itself the design
lesson the reference teaches.

This module packages that story behind three calls:

* :func:`initialize` — idempotent ``jax.distributed.initialize`` with env
  autodetection (no-op single-process);
* :func:`global_pipeline_mesh` — a ``(stage, data)`` mesh over ALL processes'
  devices, stage axis laid out within a slice so inter-stage ppermute rides
  ICI while the data axis crosses DCN (the scaling-book recipe);
* :func:`host_local_batch` — form a global array from each host's local
  shard (`jax.make_array_from_process_local_data`) for data loading.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, STAGE_AXIS

__all__ = ["initialize", "is_initialized", "global_pipeline_mesh",
           "host_local_batch", "process_summary"]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Wire up the multi-host runtime (idempotent; no-op single-process).

    With no arguments, resolution follows ``jax.distributed.initialize``'s
    env autodetection (TPU metadata / cluster env vars). Single-process runs
    (no coordinator found) proceed silently — the same code then works from
    a laptop CPU to a multi-slice pod.
    """
    global _initialized
    if _initialized:
        return
    explicit = coordinator_address is not None or num_processes is not None
    if not explicit and not _cluster_hinted():
        _initialized = True  # single-process: nothing to wire
        return
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except (ValueError, RuntimeError) as e:
        if explicit:
            raise
        import warnings
        warnings.warn(
            f"multi-host environment hinted but jax.distributed.initialize "
            f"failed ({e}); continuing single-process — every host will "
            f"train independently if this really is a pod", RuntimeWarning)
    _initialized = True


def _cluster_hinted() -> bool:
    """True when the environment names an actual multi-host cluster.

    A coordinator address env var counts; so does a TPU pod worker list with
    more than one *plausible* host (dev boxes sometimes carry a
    warning-string placeholder in TPU_WORKER_HOSTNAMES — a value with spaces
    is not a hostname list). TPU metadata-server autodetection on real pods
    still works by setting COORDINATOR_ADDRESS or calling with explicit
    args; it is not attempted blindly because on non-pod machines the probe
    can hang for minutes at import time.
    """
    if any(os.environ.get(k) for k in
           ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS")):
        return True
    workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    hosts = [h for h in workers.split(",") if h.strip() and " " not in h.strip()]
    return len(hosts) > 1


def is_initialized() -> bool:
    return _initialized


def global_pipeline_mesh(n_stages: int,
                         n_data: Optional[int] = None,
                         *,
                         devices: Optional[Sequence[jax.Device]] = None,
                         stage_across: bool = False
                         ) -> Mesh:
    """A ``(stage, data)`` mesh over every process's devices.

    Default layout: stage is the fastest-varying placement axis within a
    host/slice so the stage ring's ``collective-permute`` stays on ICI; the
    data axis absorbs the cross-host (DCN) dimension, where only gradient
    all-reduces travel — the bandwidth-optimal split for pipeline+data
    parallelism.

    ``stage_across=True`` inverts the placement: the STAGE axis spans the
    process boundary (devices laid out stage-major), so every inter-stage
    ``ppermute`` hop crosses the DCN analogue. That is the layout for
    models too large for one host's chips — the regime the reference's
    vestigial RPC layer declared future work (``pipe.py:295-302``). Costs
    per-cycle activation traffic on the slow fabric; prefer the default
    whenever the stage ring fits inside a slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    total = len(devices)
    if total % n_stages:
        raise ValueError(
            f"{total} global devices not divisible by n_stages={n_stages}")
    if n_data is None:
        n_data = total // n_stages
    if n_stages * n_data > total:
        raise ValueError(f"mesh {n_stages}x{n_data} exceeds {total} devices")
    if stage_across:
        # [stage, data] grid directly: stage contiguous over the process
        # boundary, data within a process.
        grid = np.asarray(devices[:n_stages * n_data]).reshape(n_stages,
                                                               n_data)
        return Mesh(grid, (STAGE_AXIS, DATA_AXIS))
    # [data, stage] grid transposed so stage is contiguous per data row.
    grid = np.asarray(devices[:n_stages * n_data]).reshape(n_data, n_stages)
    return Mesh(grid.T, (STAGE_AXIS, DATA_AXIS))


def host_local_batch(mesh: Mesh, local_batch: np.ndarray,
                     batch_axis: int = 0) -> jax.Array:
    """Assemble the global batch array from this process's local shard.

    Each host loads only its slice of the batch (the data-loading contract of
    every multi-host input pipeline); the result is a global array sharded
    ``P(data)`` on ``batch_axis``.
    """
    spec = [None] * local_batch.ndim
    spec[batch_axis] = DATA_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    return jax.make_array_from_process_local_data(sharding, local_batch)


def process_summary() -> str:
    """One-line topology description for logs."""
    return (f"process {jax.process_index()}/{jax.process_count()} | "
            f"{jax.local_device_count()} local / "
            f"{jax.device_count()} global devices | "
            f"backend {jax.default_backend()}")
