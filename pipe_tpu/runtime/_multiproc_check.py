"""Two-process CPU dryrun of the multi-host runtime (VERDICT r2 #8).

Launched as ``python -m pipe_tpu.runtime._multiproc_check <pid> <nprocs>
<port> <out_file>`` once per process. Each process:

* boots a 2-local-device CPU platform (so 2 processes give a 4-device
  global topology);
* wires the runtime with :func:`pipe_tpu.runtime.distributed.initialize`
  (explicit local coordinator);
* builds :func:`global_pipeline_mesh` (2 stages x 2 data) in BOTH
  layouts — default (stage within a process / ICI analogue, data across
  / DCN analogue) and ``stage_across=True`` (1 stage per process, so
  every inter-stage ppermute crosses the process boundary) — assembles
  the host-local batch via :func:`host_local_batch`, and runs ONE 1F1B
  pipeline train step (ScheduledPipeline.loss_and_grad) across both
  processes per layout;
* process 0 writes the losses to ``out_file``.

The launchers (``tests/test_multiprocess.py`` under ``PIPE_TPU_MULTIPROC=1``
and ``__graft_entry__.dryrun_multichip``, both via
:func:`launch_two_process_check`) compare the loss against the same step
computed single-process on a local 4-device mesh — the multi-host data
plane must be a pure layout choice.
"""

from __future__ import annotations

import functools
import sys


# Deterministic tiny workload shared by the 2-process run and the
# single-process reference (keys fixed; pure function of nothing).
WIDTH = 16
ROWS_PER_CHUNK = 4
CHUNKS = 2
N_STAGES = 2
N_DATA = 2


def _build(mesh):
    """Pipeline + params + FULL global batch (deterministic)."""
    import jax
    import jax.numpy as jnp

    from ..core import microbatch as mb
    from ..parallel.scheduled import ScheduledPipeline
    from ..parallel.spmd import stack_stage_params

    def stage_fn(p, h, ctx):
        return jnp.tanh(h @ p["w"] + p["b"])

    def pre_fn(p, x, ctx):
        return x

    def post_fn(p, h, x, ctx):
        return jnp.sum((h - 1.0) ** 2, axis=-1)

    ks = jax.random.split(jax.random.key(0), N_STAGES)
    params = [{"w": jax.random.normal(k, (WIDTH, WIDTH)) * 0.3,
               "b": jnp.zeros((WIDTH,))} for k in ks]
    stacked = stack_stage_params(params)
    pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                             checkpoint="except_last", schedule="1f1b")
    rows = ROWS_PER_CHUNK * CHUNKS * N_DATA
    x_full = jax.random.normal(jax.random.key(1), (rows, WIDTH))
    xs, n_rows = mb.stack_scatter(x_full, CHUNKS)   # [m, rows_g, W]
    w = mb.valid_row_mask(xs, n_rows)
    return pipe, stacked, xs, w


def _zero_step(mesh, pipe, stacked, xs, w):
    """One train step with ZeRO-1 moments sharded over the DATA axis of
    ``mesh`` — on the 2-process topology that axis SPANS the processes,
    so the partitioned Adam update and the param re-gather cross the DCN
    analogue. Returns ``(loss, checksum-of-updated-params)`` (both
    replicated scalars; layout must never change the math)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..train import zero as zero_mod

    from jax.sharding import NamedSharding, PartitionSpec as P

    tx = optax.adam(1e-2)
    shardings = zero_mod.moment_shardings(
        mesh, stacked, jax.eval_shape(tx.init, stacked))
    repl = NamedSharding(mesh, P())

    # outputs must be FULLY REPLICATED so float() works on the multihost
    # topology (a process can only fetch addressable values)
    # xs/w/params must enter as jit ARGUMENTS: on the 2-process topology
    # they span both processes, and closed-over constants cannot
    @functools.partial(jax.jit, out_shardings=(repl, repl))
    def step(params, xs, w):
        opt_state = zero_mod.constrain_moments(tx.init(params), shardings)
        loss, grads = pipe.loss_and_grad(params, {}, {}, xs, w)
        updates, opt_state = tx.update(grads[0], opt_state, params)
        new = optax.apply_updates(params, updates)
        # Fold the constrained post-update moments into the checksum:
        # an unused constrain_moments result would be dead-code-eliminated
        # by XLA and the "partitioned update rides the DCN" claim this
        # check documents would not actually be enforced.
        opt_state = zero_mod.constrain_moments(opt_state, shardings)
        checksum = sum(jnp.sum(jnp.abs(a.astype(jnp.float32)))
                       for a in jax.tree_util.tree_leaves(new))
        checksum = checksum + sum(
            jnp.sum(jnp.abs(a.astype(jnp.float32)))
            for a in jax.tree_util.tree_leaves(opt_state))
        return loss, checksum

    loss, checksum = step(stacked, xs, w)
    return float(loss), float(checksum)


def single_process_loss(devices=None):
    """Reference: the same step on a single-process 4-device mesh.
    Returns ``(loss, zero_checksum)``."""
    import jax

    from ..parallel.mesh import make_mesh

    devices = devices if devices is not None else jax.devices()[:4]
    mesh = make_mesh(N_STAGES, N_DATA, devices=devices)
    pipe, stacked, xs, w = _build(mesh)
    loss, _ = jax.jit(pipe.loss_and_grad)(stacked, {}, {}, xs, w)
    _, checksum = _zero_step(mesh, pipe, stacked, xs, w)
    return float(loss), checksum


def worker(process_id: int, num_processes: int, port: int,
           out_file: str) -> None:
    from ..utils.platform import force_cpu_platform
    force_cpu_platform(2)  # 2 local devices per process

    import jax
    import numpy as np

    from . import distributed as dist

    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=num_processes, process_id=process_id)
    assert jax.process_count() == num_processes, dist.process_summary()
    assert jax.device_count() == 2 * num_processes

    mesh = dist.global_pipeline_mesh(N_STAGES, N_DATA)
    pipe, stacked, xs_global, w_global = _build(mesh)

    # Re-create xs as if each host loaded ONLY its data shard: slice this
    # process's rows out of the deterministic global batch, then assemble
    # the global array from per-host shards (the multi-host data-loading
    # contract).
    rows_g = xs_global.shape[1]
    lo = process_id * (rows_g // num_processes)
    hi = lo + rows_g // num_processes
    xs_local = np.asarray(xs_global)[:, lo:hi]
    xs = dist.host_local_batch(mesh, xs_local, batch_axis=1)
    w = dist.host_local_batch(mesh, np.asarray(w_global)[:, lo:hi],
                              batch_axis=1)

    loss, grads = jax.jit(pipe.loss_and_grad)(stacked, {}, {}, xs, w)
    jax.block_until_ready(grads)
    # ZeRO-1 across the process-spanning data axis: the sharded update's
    # collectives ride the DCN analogue
    _, checksum = _zero_step(mesh, pipe, stacked, xs, w)

    # STAGE axis across the process boundary (1 stage per process): every
    # inter-stage ppermute hop crosses the DCN analogue — the regime the
    # reference's vestigial RPC layer declared future work
    # (``pipe.py:295-302``). The data axis is intra-process here, so every
    # process addresses the full batch.
    mesh_sx = dist.global_pipeline_mesh(N_STAGES, N_DATA, stage_across=True)
    pipe_sx, stacked_sx, xs_g_sx, w_g_sx = _build(mesh_sx)
    xs_sx = dist.host_local_batch(mesh_sx, np.asarray(xs_g_sx),
                                  batch_axis=1)
    w_sx = dist.host_local_batch(mesh_sx, np.asarray(w_g_sx), batch_axis=1)
    from jax.sharding import NamedSharding, PartitionSpec as P
    repl = NamedSharding(mesh_sx, P())
    loss_sx, grads_sx = jax.jit(
        pipe_sx.loss_and_grad,
        out_shardings=(repl, None))(stacked_sx, {}, {}, xs_sx, w_sx)
    jax.block_until_ready(grads_sx)

    if process_id == 0:
        with open(out_file, "w") as f:
            f.write(f"{float(loss)!r} {checksum!r} {float(loss_sx)!r}")


def launch_two_process_check(out_file: str, *, timeout: float = 600.0,
                             repo_root: str = None):
    """Spawn the two workers as REAL processes; returns process 0's
    ``(loss, zero_checksum)``.

    Shared by the gated test and the dryrun. Raises
    ``subprocess.TimeoutExpired``/``OSError`` when the environment cannot
    launch or connect the processes (callers may classify those as
    sandbox restrictions), and ``RuntimeError`` when a worker genuinely
    fails or breaks the output contract — never leaves orphans.
    """
    import os
    import socket
    import subprocess

    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    # Fresh interpreters must not boot the axon TPU plugin (it would hang
    # CPU selection) and must not inherit any forced device count: the
    # workers set their own 2-device CPU platform.
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = []
    try:
        procs = [subprocess.Popen(
            [sys.executable, "-m", "pipe_tpu.runtime._multiproc_check",
             str(i), "2", str(port), str(out_file)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for i in range(2)]
        texts = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:               # never leave orphaned JAX processes
            if p.poll() is None:
                p.kill()
                p.wait()
    if any(p.returncode != 0 for p in procs):
        raise RuntimeError(
            "multiproc worker failed:\n" +
            "\n".join(t.decode(errors="replace")[-3000:] for t in texts))
    try:
        with open(out_file) as f:
            loss_s, ck_s, loss_sx_s = f.read().split()
            return float(loss_s), float(ck_s), float(loss_sx_s)
    except (OSError, ValueError) as e:
        raise RuntimeError(
            f"workers exited 0 but the loss file contract broke: {e}")


if __name__ == "__main__":
    worker(int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]),
           sys.argv[4])
