"""Disaggregated prefill/decode serving: phase-specialized replicas.

A mixed replica runs both phases in one engine, so a burst of long
prompts stalls every live decode slot behind multi-chunk prefills (the
engine admits first, then runs ONE decode chunk per tick) — TTFT and
decode tail latency fight for the same host loop. Disaggregation
splits the fleet by phase instead:

* **prefill replicas** (``ReplicaSpec.role="prefill"``) run only the
  chunked-prefill program at large batch. Requests arrive clamped to
  ``max_new_tokens=1`` and retire at their first token — the engine's
  existing one-token fast path — leaving the prefix blocks cached in
  the replica's paged pool.
* **decode replicas** (``role="decode"``) run only the resident decode
  loop at high slot counts. They never prefill from scratch: the
  controller ships the prefill replica's cached prefix blocks through
  the existing ``export_prefix_payload``/``import_prefix_payload``
  path (raw bytes in-process, int8 across the wire) before placement,
  and a decode-only engine refuses a cold multi-block prompt outright
  (``serve/engine.py:_check_phase``).
* **mixed replicas** stay what they always were, and are the fallback
  for either phase when a role pool is empty or entirely sick.

:class:`DisaggController` subclasses :class:`~.control.FleetController`
and keeps every invariant it proved — one front queue, the health
machine, retry budgets, and most importantly the exactly-once delivery
ledger. The two-phase flow is built from pieces the base controller
already has:

1. ``submit`` stashes the caller's ``max_new_tokens``, clamps the
   request to 1 token and tags ``req.phase="prefill"``; role-aware
   placement (``_role_filter``) routes it to the prefill pool.
2. The prefill replica retires the request after its first token — a
   **shadow terminal**. ``_deliver`` intercepts it before the ledger:
   the response is consumed (never client-visible), the request flips
   to ``phase="decode"`` with its original budget restored, and
   re-enters placement through the parked queue. Consuming the shadow
   also pops ``_placed_on``, so a prefill replica dying later cannot
   reclaim (and double-place) a request that already moved on.
3. Decode placement ships the KV prefix (warm-probe first, exactly the
   PR 10 handoff discipline) and places. The decode replica resumes
   from the seated blocks and generates the full budget; with the same
   per-request seed the first token is regenerated identically, so no
   stream stitching is needed. Only this terminal reaches the client.

Failure anywhere routes through the base controller's one
park-or-finish gate (``reclaim``): a prefill replica SIGKILLed before
the shadow is polled has it salvaged off the dead wire and consumed
the same way; killed after export but before the decode import
acknowledges, the ship simply comes up cold and the request falls back
to a mixed replica for an ordinary prefill — one delivery either way.

:func:`suggest_roles` is the cost-driven planner: it sizes the
prefill:decode split from measured per-phase token costs (the
telemetry the engine already records — TTFT and per-token decode
histograms) instead of by hand.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from ..obs.events import REQUEST
from ..obs.telemetry import get_registry, labelled
from .control import RETIRED, FleetController, Replica, TransportError

if False:  # type-hint names only (serve imports stay lazy, see control.py)
    from ..serve.queue import Request, Response  # noqa: F401

__all__ = ["DisaggController", "RoleSuggestion", "suggest_roles"]


class DisaggController(FleetController):
    """Phase-aware fleet controller: every request flows
    prefill → KV handoff → decode across role-specialized replicas.

    Construction is the base controller's: pass transports whose
    ``role`` attributes carry the split (``ReplicaSpec.role`` for
    process replicas, the ``role=`` kwarg or the engine's ``phase``
    for in-process ones). A fleet of only mixed replicas degenerates
    to two placements per request on the same pool — correct, just
    pointless — so deployments gate on ``suggest_roles`` first.
    """

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # per-request disagg state, keyed by request id. Entries live
        # from submit to the CLIENT-VISIBLE terminal (the decode
        # phase's, or a genuine failure in either phase).
        self._orig_max_new: Dict[int, int] = {}
        self._prefill_on: Dict[int, int] = {}   # id -> prefill replica
        # shadow tokens consumed (never delivered): the observer adds
        # these to the delivered side of its token reconciliation,
        # because the prefill replica's obs_tokens_out counted them
        self.obs_shadow_tokens = 0

    # -- front door --------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None, seed: int = 0,
               priority: int = 0, timeout_s: Optional[float] = None,
               session: Optional[str] = None):
        """Validate/enqueue like the base controller (against the full
        token budget), then clamp the request to its prefill phase."""
        req = super().submit(prompt, max_new_tokens=max_new_tokens,
                             seed=seed, priority=priority,
                             timeout_s=timeout_s, session=session)
        self._orig_max_new[req.id] = req.max_new_tokens
        req.max_new_tokens = 1
        req.phase = "prefill"
        get_registry().counter("serve.fleet.disagg_submitted").inc()
        return req

    # -- the shadow-terminal interception ----------------------------------

    def _deliver(self, resp):
        rid = resp.request_id
        req = self._tracked.get(rid)
        if (req is not None and req.phase == "prefill"
                and resp.status == "ok"):
            return self._consume_shadow(req, resp)
        # genuine terminal (decode finished, or a failure in either
        # phase): drop the disagg state and deliver exactly once
        self._orig_max_new.pop(rid, None)
        self._prefill_on.pop(rid, None)
        return super()._deliver(resp)

    def _consume_shadow(self, req, resp) -> None:
        """The prefill phase's one-token terminal: never delivered.
        Remember where the prefix now lives, restore the caller's
        budget, flip the request to its decode phase and re-enter it
        through the parked queue (eligible immediately — backoff is for
        failures; this is progress). Popping ``_placed_on`` here is the
        exactly-once hinge: the request is no longer "in flight" on the
        prefill replica, so a later transport drop there reclaims
        nothing for it."""
        if self.journal is not None:
            # durable BEFORE the hinge: a crash after this record knows
            # the request crossed into its decode phase (and where the
            # prefix lives), a crash before it replays the prefill
            self.journal.append(
                "shadow", request=req.id,
                src=self._placed_on.get(req.id),
                max_new_tokens=self._orig_max_new.get(
                    req.id, req.max_new_tokens))
        src = self._placed_on.pop(req.id, None)
        if src is not None:
            self._prefill_on[req.id] = src
        req.max_new_tokens = self._orig_max_new.get(
            req.id, req.max_new_tokens)
        req.phase = "decode"
        self.obs_shadow_tokens += len(resp.tokens)
        now = self.clock()
        self._parked.append((now, req))
        reg = get_registry()
        reg.counter("serve.fleet.disagg_prefill_done").inc()
        if src is not None:
            role = self.replicas[src].role
            reg.counter(labelled("serve.fleet.handoff_requests",
                                 role=role)).inc()
        self.events.event(REQUEST, request=req.id, trace=req.trace_id,
                          stage="handoff", replica=src,
                          attempts=req.attempts,
                          tokens=len(resp.tokens))
        return None

    # -- crash recovery ----------------------------------------------------

    def _restore_phase(self, req, state) -> None:
        """Rebuild the disagg tags for one recovered orphan. The
        journal's ``submit`` record carries the FULL budget (the base
        controller journals before this class clamps), so: a ``shadow``
        record means the request already crossed the hinge — restore
        the budget and re-enter as its decode phase, remembering the
        prefix source; no shadow record means the prefill never
        finished — re-clamp to one token and replay the prefill."""
        self._orig_max_new[req.id] = req.max_new_tokens
        rec = state.shadow.get(req.id)
        if rec is not None:
            req.phase = "decode"
            src = rec.get("src")
            if src is not None:
                self._prefill_on[req.id] = int(src)
        else:
            req.phase = "prefill"
            req.max_new_tokens = 1

    def _salvage(self, rep, resp):
        """Replayed responses are phase-ambiguous on a disagg fleet: a
        prefill child's retained window holds SHADOW frames, and a
        shadow for a request whose hinge is already journaled is a
        duplicate — consuming it again would restart the decode phase
        a decode child may be about to answer. Only a shadow the crash
        interrupted (the request still tagged ``prefill``) is progress;
        everything else from a prefill child is dropped, and decode
        children salvage as usual."""
        req = self._tracked.get(resp.request_id)
        if resp.status == "ok" and (req is None or req.phase != "prefill"):
            if rep.role == "prefill":
                return None           # prefill children never hold terminals
            if (rep.role == "mixed" and len(resp.tokens) <= 1
                    and req is not None and req.max_new_tokens > 1):
                return None           # a mixed child's replayed shadow
        return self._deliver(resp)

    # -- decode placement (KV ship + fallbacks) ----------------------------

    def _try_place(self, req, now: float) -> bool:
        if req.phase == "decode":
            return self._place_decode(req, now)
        return super()._try_place(req, now)

    def _place_decode(self, req, now: float) -> bool:
        """Place the decode phase: choose from the decode pool (mixed
        as fallback), ship the prefix from the prefill replica unless
        the target is already warm, then place. A decode-only engine
        that still refuses (the ship came up cold — prefill replica
        dead, pool mismatch, prefix evicted) falls back to a mixed
        replica, which re-prefills like any ordinary request; no mixed
        replica either → the request flips back to its prefill phase
        for a fresh prefix (never parked-forever in a static fleet)."""
        placeable = self._placeable()
        candidates = self._role_filter(req, placeable)
        if not candidates:
            return False
        rep = self._choose(req, candidates)
        src = self._prefill_on.get(req.id)
        if src is not None and src != rep.index:
            self._ship_prefix(req, src, rep)
        try:
            rep.transport.place(req)        # increments req.attempts
        except TransportError:
            self._transport_drop(rep, now)
            return False
        except ValueError:
            if rep.role != "decode":
                raise                       # mixed refused: genuine
            get_registry().counter(
                "serve.fleet.disagg_decode_refused").inc()
            mixed = [r for r in placeable if r.role == "mixed"]
            if not mixed:
                # "Parked until a mixed replica recovers" is FOREVER in
                # a static prefill/decode fleet: the prefix is gone
                # (evicted under pool pressure, or the source died) and
                # every retry re-fails identically. Send the request
                # back through the prefill phase instead — re-clamp,
                # forget the stale source, and the parked queue
                # re-enters it on the prefill pool for a fresh prefix.
                # Exactly-once holds (no decode placement happened) and
                # the per-request retry budget still bounds the loop.
                self._prefill_on.pop(req.id, None)
                req.max_new_tokens = 1
                req.phase = "prefill"
                get_registry().counter(
                    "serve.fleet.disagg_reprefill").inc()
                return False
            rep = min(mixed, key=lambda r: (r.load, r.index))
            get_registry().counter(
                "serve.fleet.disagg_mixed_fallback").inc()
            try:
                rep.transport.place(req)
            except TransportError:
                self._transport_drop(rep, now)
                return False
        self._placed_on[req.id] = rep.index
        self.events.event(REQUEST, request=req.id, trace=req.trace_id,
                          stage="placed", replica=rep.index,
                          attempts=req.attempts, phase="decode")
        return True

    def _ship_prefix(self, req, src_idx: int, rep: Replica) -> bool:
        """Move the request's cached prefix blocks from the prefill
        replica to the decode target — warm-probe first (PR 10
        discipline: record what the handoff COST, not what it did),
        then export/import. Every failure degrades to cold: the caller
        decides whether cold is acceptable (mixed target re-prefills)
        or grounds for fallback (decode target refuses). True when the
        target ends up warm."""
        reg = get_registry()
        warm = 0
        try:
            warm = rep.transport.cached_prefix_blocks(req.prompt)
        except TransportError:
            pass
        if warm:
            reg.counter("serve.fleet.disagg_handoff_warm").inc()
            return True
        payload = None
        src_rep = self.replicas[src_idx]
        if src_rep.state != RETIRED:
            try:
                payload = src_rep.transport.export_prefix(req.prompt)
            except TransportError:
                payload = None      # died mid-export: ship nothing
        seated = nbytes = 0
        if payload is not None:
            nbytes = int(payload.get("nbytes", 0))
            try:
                seated = rep.transport.import_prefix(payload)
            except TransportError:
                seated = 0          # died mid-import: target is cold
        if seated:
            reg.counter("serve.fleet.disagg_handoff_shipped").inc(seated)
            reg.counter("serve.fleet.disagg_handoff_bytes").inc(nbytes)
            reg.gauge(labelled("serve.fleet.handoff_bytes",
                               replica=rep.index,
                               role=rep.role)).set(nbytes)
        else:
            reg.counter("serve.fleet.disagg_handoff_cold").inc()
        self.events.event("resilience", action="disagg_kv_ship",
                          request=req.id, from_replica=src_idx,
                          to_replica=rep.index, shipped_blocks=seated,
                          bytes=nbytes, trace=req.trace_id,
                          stage="handoff", attempts=req.attempts)
        return seated > 0


# ---------------------------------------------------------------------------
# the cost-driven role planner


@dataclasses.dataclass(frozen=True)
class RoleSuggestion:
    """What :func:`suggest_roles` decided and why. ``roles`` is
    index-aligned with the fleet's transports; ``prefill_frac`` is the
    prefill share of per-request compute the split was sized from;
    ``source`` records where the per-token costs came from
    (``"args"``, ``"telemetry"``, or ``"uniform"`` when neither had
    data)."""

    roles: List[str]
    n_prefill: int
    n_decode: int
    prefill_frac: float
    prefill_token_s: float
    decode_token_s: float
    source: str


def suggest_roles(n_replicas: int, *, prompt_len: int,
                  max_new_tokens: int,
                  prefill_token_s: Optional[float] = None,
                  decode_token_s: Optional[float] = None,
                  registry=None) -> RoleSuggestion:
    """Size the prefill:decode split from measured phase costs.

    The prefill share of one request's compute is
    ``f = L_p * c_p / (L_p * c_p + L_d * c_d)`` for expected prompt
    length ``L_p``, token budget ``L_d`` and per-token costs ``c_p``
    (prefill) and ``c_d`` (decode). The fleet should put ``round(f*n)``
    replicas on prefill — clamped to ``[1, n-1]`` so neither pool is
    empty — because a pool sized below its compute share becomes the
    bottleneck and the other idles (the pipeline-planning argument:
    stage shares should track measured stage costs, not symmetry).

    Costs default from the serving telemetry already being recorded:
    ``serve.engine.ttft_sec`` (mean TTFT / prompt length approximates
    the per-token prefill cost — TTFT is dominated by the prefill
    chunks) and ``serve.engine.token_sec`` (mean per-token decode
    latency). Pass ``prefill_token_s``/``decode_token_s`` to override
    (a bench measuring them directly, or capacity planning for a
    workload not yet served). With no telemetry and no overrides the
    costs fall back to uniform (``f`` is then just the token-count
    ratio). Fleets of fewer than two replicas stay all-mixed — there
    is nothing to specialize.
    """
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    if prompt_len < 1 or max_new_tokens < 1:
        raise ValueError(
            f"prompt_len and max_new_tokens must be >= 1, got "
            f"{prompt_len} and {max_new_tokens}")
    source = "args"
    if prefill_token_s is None or decode_token_s is None:
        reg = registry if registry is not None else get_registry()
        ttft = reg.histogram("serve.engine.ttft_sec")
        toks = reg.histogram("serve.engine.token_sec")
        if prefill_token_s is None and ttft.count:
            prefill_token_s = (ttft.sum / ttft.count) / max(1, prompt_len)
            source = "telemetry"
        if decode_token_s is None and toks.count:
            decode_token_s = toks.sum / toks.count
            source = "telemetry"
    if prefill_token_s is None or decode_token_s is None \
            or prefill_token_s <= 0 or decode_token_s <= 0:
        prefill_token_s = decode_token_s = 1.0
        source = "uniform"
    pre = prompt_len * prefill_token_s
    dec = max_new_tokens * decode_token_s
    frac = pre / (pre + dec)
    if n_replicas < 2:
        return RoleSuggestion(roles=["mixed"] * n_replicas, n_prefill=0,
                              n_decode=0, prefill_frac=frac,
                              prefill_token_s=prefill_token_s,
                              decode_token_s=decode_token_s,
                              source=source)
    n_pre = min(max(int(round(frac * n_replicas)), 1), n_replicas - 1)
    return RoleSuggestion(
        roles=["prefill"] * n_pre + ["decode"] * (n_replicas - n_pre),
        n_prefill=n_pre, n_decode=n_replicas - n_pre, prefill_frac=frac,
        prefill_token_s=prefill_token_s, decode_token_s=decode_token_s,
        source=source)
