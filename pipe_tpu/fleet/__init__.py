"""pipe_tpu.fleet — the process-separated serving fleet.

The coordination plane for N serve-engine replicas, split from the
transport that reaches them:

* :mod:`.control` — the transport-agnostic control plane.
  :class:`~.control.FleetController` owns placement, the
  HEALTHY→SUSPECT→WEDGED→DRAINING→RETIRED health machine, retry
  budgets and the exactly-once delivery ledger — everything
  ``serve/router.py`` proved in-process, now speaking to replicas only
  through the :class:`~.control.ReplicaTransport` interface.
  :class:`~.control.InProcessTransport` preserves the PR 7 behavior
  byte-for-byte (serial ticks) and adds an async mode (one tick thread
  per replica, so a slow replica no longer stalls its siblings).
* :mod:`.proc` — :class:`~.proc.ProcessReplicaTransport`: each replica
  a real OS process owning its own engine, jit cache and KV pool,
  speaking a length-prefixed msgpack/JSON wire protocol with
  heartbeats that carry the health signals across the IPC boundary.
* :mod:`.topology` — carve a dp×pp sub-mesh per replica from the
  global device set ("model parallel between nodes is bad": a replica
  never spans a host).

``serve/router.py``'s :class:`~..serve.router.Router` is now a thin
shim over this package — existing callers and the pinned
``tests/test_router.py`` suite are unchanged. See ``docs/fleet.md``.
"""

from .control import (DRAINING, HEALTHY, RETIRED, SUSPECT, WEDGED,
                      FleetController, InProcessTransport, Replica,
                      ReplicaHealth, ReplicaTransport, RouterPolicy,
                      TransportError)
from .disagg import DisaggController, RoleSuggestion, suggest_roles
from .journal import JournalState, RequestJournal
from .proc import (FleetSpawnError, ProcessReplicaTransport, ReplicaSpec,
                   check_spawn_capability)
from .topology import (carve_replica_meshes, carve_role_meshes,
                       replica_device_plan, role_device_plan)

__all__ = ["FleetController", "DisaggController", "ReplicaTransport",
           "InProcessTransport", "Replica", "ReplicaHealth", "RouterPolicy",
           "TransportError", "RoleSuggestion", "suggest_roles",
           "RequestJournal", "JournalState",
           "ProcessReplicaTransport", "ReplicaSpec", "FleetSpawnError",
           "check_spawn_capability", "carve_replica_meshes",
           "carve_role_meshes", "replica_device_plan", "role_device_plan",
           "HEALTHY", "SUSPECT", "WEDGED", "DRAINING", "RETIRED"]
