"""Mesh-aware replica spawn plans: carve the device grid into fleets.

A serving fleet multiplies the pipeline topology: each replica wants
its OWN ``(stage, data)`` sub-mesh (``runtime/distributed
.global_pipeline_mesh`` shape), and replicas must not interleave
devices — a replica that straddles two processes would put its stage
ring's ``ppermute`` on the cross-host fabric AND couple its failure
domain to a neighbour's. The carve here is therefore contiguous and
process-aligned: replica *i* owns devices
``[i*per, (i+1)*per)`` of the (process-major) global device list, so a
replica either fits inside one process or owns whole processes — never
a fractional share of two.

:func:`replica_device_plan` is the pure planning half (validation +
index ranges, no jax import needed beyond the device list);
:func:`carve_replica_meshes` materializes one
:class:`jax.sharding.Mesh` per replica via the same
``global_pipeline_mesh`` builder the trainer uses, so every sub-mesh
inherits the stage-on-ICI / data-on-DCN axis discipline.

The process transport composes with this per-replica: a spawn plan's
``local_devices`` count feeds :class:`~.proc.ReplicaSpec` so each
child interpreter forces exactly its share of (host) devices — on CPU
that is the ``--xla_force_host_platform_device_count`` trick, on real
hardware each child process would enumerate only its visible chips.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

__all__ = ["ReplicaDevices", "RoleReplicaDevices", "replica_device_plan",
           "role_device_plan", "carve_replica_meshes", "carve_role_meshes"]

_ROLES = ("prefill", "decode", "mixed")


@dataclasses.dataclass(frozen=True)
class ReplicaDevices:
    """One replica's slice of the device grid: global-list index range
    ``[start, stop)`` plus the (n_stages, n_data) mesh shape it will be
    folded into."""

    index: int
    start: int
    stop: int
    n_stages: int
    n_data: int

    @property
    def n_devices(self) -> int:
        return self.stop - self.start


def replica_device_plan(n_replicas: int, n_stages: int,
                        n_data: Optional[int] = None, *,
                        n_devices: Optional[int] = None,
                        devices_per_process: Optional[int] = None
                        ) -> List[ReplicaDevices]:
    """Split ``n_devices`` into ``n_replicas`` contiguous
    ``n_stages x n_data`` sub-meshes; raises ``ValueError`` with the
    arithmetic spelled out when the grid doesn't divide.

    ``devices_per_process`` (when known) adds the process-alignment
    check: each replica's share must be a multiple OR a divisor of one
    process's device count, so no replica takes a fractional share of
    two processes.
    """
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    if n_devices % n_replicas:
        raise ValueError(
            f"{n_devices} devices do not split over {n_replicas} "
            f"replicas ({n_devices} % {n_replicas} != 0)")
    per = n_devices // n_replicas
    if per % n_stages:
        raise ValueError(
            f"each replica's {per} devices do not fold into "
            f"n_stages={n_stages} ({per} % {n_stages} != 0)")
    data = per // n_stages if n_data is None else n_data
    if n_stages * data != per:
        raise ValueError(
            f"replica mesh {n_stages}x{data} needs {n_stages * data} "
            f"devices but each replica owns {per}")
    if devices_per_process is not None and devices_per_process > 0:
        if per % devices_per_process and devices_per_process % per:
            raise ValueError(
                f"replica share of {per} devices straddles the process "
                f"boundary ({devices_per_process} devices/process): a "
                f"replica must own whole processes or fit inside one")
    return [ReplicaDevices(index=i, start=i * per, stop=(i + 1) * per,
                           n_stages=n_stages, n_data=data)
            for i in range(n_replicas)]


@dataclasses.dataclass(frozen=True)
class RoleReplicaDevices(ReplicaDevices):
    """One role-specialized replica's slice of the grid. Unlike the
    symmetric plan, role plans are asymmetric by design: a prefill
    replica typically takes a wide data axis (large-batch chunked
    prefill is throughput-bound), a decode replica a deep slot count on
    fewer chips (the resident ``while_loop`` is latency-bound), so
    shares differ per replica."""

    role: str = "mixed"


def role_device_plan(specs: Sequence, *,
                     n_devices: Optional[int] = None,
                     devices_per_process: Optional[int] = None
                     ) -> List[RoleReplicaDevices]:
    """Carve the device grid into role-asymmetric contiguous sub-meshes.

    ``specs`` is one entry per replica: ``(role, n_stages, n_data)``
    tuples or ``{"role", "n_stages", "n_data"}`` dicts, in placement
    order. Each replica owns exactly ``n_stages * n_data`` devices —
    shares may differ between replicas (that is the point) — and the
    plan must consume the grid exactly: ``sum(shares) == n_devices``.

    Process alignment is the same discipline as the symmetric plan but
    checked per-slice, because unequal shares can misalign even when
    every share individually divides the process size: a replica either
    fits inside one process (its slice does not cross a process
    boundary) or owns whole processes (starts on a boundary and spans a
    multiple of ``devices_per_process``).
    """
    if not specs:
        raise ValueError("role_device_plan needs at least one replica spec")
    norm: List[tuple] = []
    for i, spec in enumerate(specs):
        if isinstance(spec, dict):
            role = spec.get("role", "mixed")
            ns, nd = spec.get("n_stages", 1), spec.get("n_data", 1)
        else:
            role, ns, nd = spec
        if role not in _ROLES:
            raise ValueError(
                f"replica {i}: role must be one of {_ROLES}, got {role!r}")
        ns, nd = int(ns), int(nd)
        if ns < 1 or nd < 1:
            raise ValueError(
                f"replica {i}: mesh shape {ns}x{nd} is not positive")
        norm.append((str(role), ns, nd))
    need = sum(ns * nd for _, ns, nd in norm)
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    if need != n_devices:
        raise ValueError(
            f"role plan wants {need} devices (sum of n_stages*n_data "
            f"over {len(norm)} replicas: "
            f"{[ns * nd for _, ns, nd in norm]}) but the grid has "
            f"{n_devices}")
    plan: List[RoleReplicaDevices] = []
    start = 0
    dpp = devices_per_process
    for i, (role, ns, nd) in enumerate(norm):
        per = ns * nd
        if dpp is not None and dpp > 0:
            if per >= dpp:
                if per % dpp or start % dpp:
                    raise ValueError(
                        f"replica {i} ({role}) owns {per} devices from "
                        f"index {start}: a multi-process replica must "
                        f"start on a process boundary and span whole "
                        f"processes ({dpp} devices/process)")
            elif start // dpp != (start + per - 1) // dpp:
                raise ValueError(
                    f"replica {i} ({role}) owns devices [{start}, "
                    f"{start + per}) which straddle the process boundary "
                    f"at {((start // dpp) + 1) * dpp} ({dpp} "
                    f"devices/process): a sub-process replica must fit "
                    f"inside one process")
        plan.append(RoleReplicaDevices(index=i, start=start,
                                       stop=start + per, n_stages=ns,
                                       n_data=nd, role=role))
        start += per
    return plan


def carve_role_meshes(specs: Sequence, *,
                      devices: Optional[Sequence] = None,
                      stage_across: bool = False) -> list:
    """One ``(stage, data)`` mesh per role-specialized replica, carved
    contiguously per :func:`role_device_plan` — index-aligned with the
    plan, same axis discipline as :func:`carve_replica_meshes`."""
    import jax

    from ..runtime.distributed import global_pipeline_mesh
    devices = list(devices if devices is not None else jax.devices())
    plan = role_device_plan(specs, n_devices=len(devices))
    return [global_pipeline_mesh(
                rd.n_stages, rd.n_data,
                devices=devices[rd.start:rd.stop],
                stage_across=stage_across)
            for rd in plan]


def carve_replica_meshes(n_replicas: int, n_stages: int,
                         n_data: Optional[int] = None, *,
                         devices: Optional[Sequence] = None,
                         stage_across: bool = False) -> list:
    """One ``(stage, data)`` :class:`jax.sharding.Mesh` per replica,
    carved contiguously from ``devices`` (default: all global devices)
    through the same builder the trainer uses — returns a list of
    meshes, index-aligned with the plan from
    :func:`replica_device_plan`."""
    import jax

    from ..runtime.distributed import global_pipeline_mesh
    devices = list(devices if devices is not None else jax.devices())
    plan = replica_device_plan(n_replicas, n_stages, n_data,
                               n_devices=len(devices))
    return [global_pipeline_mesh(
                n_stages, rd.n_data,
                devices=devices[rd.start:rd.stop],
                stage_across=stage_across)
            for rd in plan]
