"""Durable request journal: the control plane's lifecycle ledger as a
replayable on-disk artifact.

The fleet survives any *replica* dying (the SIGKILL drills in
FLEET_r15/r19), but the controller's exactly-once ledger, retry parks
and disagg phase tags live in parent memory — kill the parent and
every in-flight id is stranded. :class:`RequestJournal` fixes that the
same way ``train/state.py`` makes training restartable: every
lifecycle transition (submit, place, shadow-consume, park, deliver) is
appended to an fsync'd JSONL write-ahead log *before* the controller
acts on it, and :meth:`RequestJournal.recover` replays the log into a
:class:`JournalState` a fresh controller can rebuild itself from
(``FleetController.from_journal``).

Durability discipline, borrowed from ``train/state.py``:

* every appended record is flushed AND ``os.fsync``'d before the
  controller takes the journaled action — a SIGKILL between journal
  and action replays the action; a SIGKILL between action and the
  *next* journal record is reconciled against the live replicas
  (the rejoin handshake in ``fleet/proc.py`` asks each surviving
  child what it still holds);
* the ``fleet.json`` rejoin snapshot (replica wire coordinates) is
  written through the tmp + rename + dir-fsync sequence, so readers
  never observe a half-written file;
* :meth:`recover` tolerates a torn FINAL line — the one a crash
  mid-append can legally produce — and refuses a torn *middle* line
  loudly, mirroring :meth:`pipe_tpu.obs.events.EventLog.read` exactly.

Record kinds (one JSON object per line, ``kind`` keyed):

==================  =====================================================
``open``            a journal writer attached (restart appends, so a log
                    may hold several)
``replica``         wire coordinates of one child replica — port, token,
                    pid, role, spec — everything the parent-side rejoin
                    handshake needs to re-dial a *running* child
``submit``          request accepted at the front door (full budget,
                    pre-clamp for disagg)
``place``           about to place on replica N (attempts = replay count)
``shadow``          disagg shadow-consume: prefill terminal swallowed,
                    request re-entering as its decode phase
``park``            about to park for backoff retry
``deliver``         about to record a terminal response (the
                    exactly-once hinge)
``clean_shutdown``  drain completed and the journal closed clean —
                    restart can skip reconciliation entirely
==================  =====================================================
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["RequestJournal", "JournalState", "JOURNAL_FILENAME",
           "META_FILENAME"]

JOURNAL_FILENAME = "journal.jsonl"
META_FILENAME = "fleet.json"

RECORD_KINDS = ("open", "replica", "submit", "place", "shadow", "park",
                "deliver", "clean_shutdown")


def _atomic_write_json(target: str, doc: dict) -> None:
    """tmp + rename + fsync (file AND directory), the ``train/state.py``
    discipline: a reader never sees a partial document and the rename
    survives power loss once the directory entry is synced."""
    d = os.path.dirname(target) or "."
    tmp = os.path.join(d, f".{os.path.basename(target)}.tmp")
    data = json.dumps(doc, indent=2, sort_keys=True).encode("utf-8")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, target)
    try:
        dfd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class JournalState:
    """The replayed journal: everything a fresh controller needs to
    rebuild its exactly-once ledger, retry parks and phase tags.

    ``requests``   id -> the ``submit`` record (full pre-clamp budget)
    ``terminal``   id -> the ``deliver`` record (already answered —
                   recovery instates a ledger stub so a duplicate
                   delivery still raises)
    ``placed_on``  id -> replica index of the LAST un-consumed
                   placement (reconciled against the live child)
    ``attempts``   id -> number of journaled placements (the retry
                   budget already spent)
    ``shadow``     id -> the ``shadow`` record for requests that
                   crossed the disagg prefill->decode hinge
    ``replicas``   index -> the latest ``replica`` wire record
    ``clean``      True iff the log ENDS with ``clean_shutdown``
    """

    def __init__(self) -> None:
        self.requests: Dict[int, dict] = {}
        self.terminal: Dict[int, dict] = {}
        self.placed_on: Dict[int, int] = {}
        self.attempts: Dict[int, int] = {}
        self.shadow: Dict[int, dict] = {}
        self.replicas: Dict[int, dict] = {}
        self.clean = False
        self.records = 0

    @property
    def orphans(self) -> List[int]:
        """Submitted ids with no terminal record — the in-flight set
        the crash stranded, in id order."""
        return sorted(i for i in self.requests if i not in self.terminal)

    @property
    def max_request_id(self) -> int:
        return max(self.requests, default=-1)

    def apply(self, rec: dict) -> None:
        kind = rec.get("kind")
        self.records += 1
        self.clean = kind == "clean_shutdown"
        if kind == "replica":
            self.replicas[int(rec["replica"])] = rec
        elif kind == "submit":
            self.requests[int(rec["request"])] = rec
        elif kind == "place":
            rid = int(rec["request"])
            self.placed_on[rid] = int(rec["replica"])
            self.attempts[rid] = self.attempts.get(rid, 0) + 1
        elif kind == "shadow":
            rid = int(rec["request"])
            self.shadow[rid] = rec
            # the shadow-consume pops the placement: the prefill slot
            # retired and the decode phase has not been placed yet
            self.placed_on.pop(rid, None)
        elif kind == "park":
            self.placed_on.pop(int(rec["request"]), None)
        elif kind == "deliver":
            rid = int(rec["request"])
            self.terminal[rid] = rec
            self.placed_on.pop(rid, None)


class RequestJournal:
    """Append-only, fsync'd JSONL write-ahead log of request lifecycle
    transitions. ``path`` is a directory (the journal lives at
    ``<path>/journal.jsonl`` with the ``fleet.json`` rejoin snapshot
    beside it) or an explicit ``*.jsonl`` file path. Opening an
    existing journal appends — restart continues the same history.

    ``fsync=False`` drops the per-record fsync (tests on tmpfs); the
    default matches the WAL contract.
    """

    def __init__(self, path: str, *, fsync: bool = True):
        if path.endswith(".jsonl"):
            self.dir = os.path.dirname(path) or "."
            self.path = path
        else:
            self.dir = path
            self.path = os.path.join(path, JOURNAL_FILENAME)
        os.makedirs(self.dir, exist_ok=True)
        self._fsync = fsync
        self._lock = threading.Lock()
        self._fh = open(self.path, "ab")
        self.records_written = 0
        self.bytes_written = 0
        self.last_fsync_at: Optional[float] = None
        self._closed = False
        self.append("open", wall_time=time.time())

    # -- writing -----------------------------------------------------------

    def append(self, kind: str, **fields: Any) -> None:
        """Journal one transition: serialize, append, flush, fsync —
        durable before the caller acts on it."""
        if kind not in RECORD_KINDS:
            raise ValueError(
                f"unknown journal record kind {kind!r}; one of "
                f"{RECORD_KINDS}")
        rec = {"kind": kind}
        rec.update(fields)
        line = json.dumps(rec, sort_keys=True).encode("utf-8") + b"\n"
        with self._lock:
            if self._closed:
                return
            self._fh.write(line)
            self._fh.flush()
            if self._fsync:
                os.fsync(self._fh.fileno())
            self.records_written += 1
            self.bytes_written += len(line)
            self.last_fsync_at = time.monotonic()

    def record_replica(self, index: int, **info: Any) -> None:
        """Journal one replica's wire coordinates (port, token, pid,
        role, spec, ...) and refresh the ``fleet.json`` rejoin snapshot
        through the tmp+rename discipline."""
        self.append("replica", replica=int(index), **info)
        try:
            state = self.recover(self.path)
        except Exception:
            return
        _atomic_write_json(
            os.path.join(self.dir, META_FILENAME),
            {"journal": self.path,
             "replicas": {str(i): r for i, r in state.replicas.items()}})

    def close(self, clean: bool = False) -> None:
        """Close the journal; ``clean=True`` stamps a final
        ``clean_shutdown`` record so restart skips reconciliation."""
        if clean:
            self.append("clean_shutdown", wall_time=time.time())
        with self._lock:
            if not self._closed:
                self._closed = True
                self._fh.close()

    # -- gauges ------------------------------------------------------------

    @property
    def fsync_age_s(self) -> Optional[float]:
        """Seconds since the last durable record (None before the
        first) — the journal-lag gauge ``fleet_top`` renders."""
        if self.last_fsync_at is None:
            return None
        return max(time.monotonic() - self.last_fsync_at, 0.0)

    # -- replay ------------------------------------------------------------

    @staticmethod
    def recover(path: str) -> JournalState:
        """Replay a journal into a :class:`JournalState`. Tolerates a
        torn FINAL line (a crash mid-append) by stopping in front of
        it; a torn line anywhere ELSE raises ``json.JSONDecodeError``
        loudly — that is corruption, not a crash artifact. Mirrors
        :meth:`pipe_tpu.obs.events.EventLog.read`."""
        if not path.endswith(".jsonl"):
            path = os.path.join(path, JOURNAL_FILENAME)
        state = JournalState()
        if not os.path.exists(path):
            return state
        with open(path, "r", encoding="utf-8") as fh:
            lines = [ln.strip() for ln in fh.read().splitlines()]
        while lines and not lines[-1]:
            lines.pop()
        for i, ln in enumerate(lines):
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break           # torn final line: crash mid-append
                raise               # torn middle line: refuse loudly
            state.apply(rec)
        return state
