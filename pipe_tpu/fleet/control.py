"""Fleet control plane: placement, health, exactly-once — transport-split.

PR 7's Router proved the fleet state machine against in-process engine
replicas driven by one serial host loop. This module is that machine
extracted from its transport, so the same implementation coordinates
in-process engines (byte-for-byte the PR 7 behavior, pinned by
``tests/test_router.py``) and real OS processes (:mod:`.proc`):

* :class:`ReplicaTransport` — everything the control plane needs from a
  replica: place/poll/evict/drain/cancel, a queue-pressure surface for
  placement, a :class:`ReplicaHealth` snapshot (the watchdog signals,
  shipped as heartbeat payload when an IPC boundary intervenes), and
  the KV-handoff hooks (export/import/invalidate prefix blocks).
* :class:`InProcessTransport` — wraps one :class:`~..serve.engine
  .ServeEngine`. Serial mode (default): ``poll()`` *is* ``engine.tick()``
  — the control loop drives the replica, exactly the PR 7 round-robin.
  Async mode (``async_tick=True``): a daemon thread ticks the engine
  continuously under a per-replica lock and ``poll()`` merely drains
  finished responses, so one slow replica no longer stalls its siblings
  (a process replica ticks *itself* — same contract, different
  mechanism).
* :class:`FleetController` — the state machine itself. Health states::

      HEALTHY --(slow streak / decode error / retryable failure)--> SUSPECT
      SUSPECT --(recover_healthy_ticks clean ticks)--> HEALTHY
      HEALTHY|SUSPECT --(wedge thresholds / heartbeat loss)--> WEDGED
      WEDGED --(queued work evicted, drain() issued)--> DRAINING
      DRAINING --(transport.drained)--> RETIRED

  plus the retry-parking/backoff machinery and the exactly-once
  delivery ledger. **The ledger lives here**, never in a transport: a
  transport may die mid-flight (socket drop, child crash) and the
  controller reclaims the in-flight requests it placed there
  (``_placed_on`` is the authoritative in-flight map), re-places them
  under ``Request.attempts``, and still delivers every id exactly once
  — a duplicate terminal response raises.

KV handoff is real here, not just counters: when a session remaps off
its home replica, the controller asks the old home's transport to
export the session's cached shared-prefix blocks (serialized through
the int8 path when they cross a process boundary — see
``serve/engine.py:export_prefix_payload``) and seats them into the
destination pool before the request is placed, so the destination
prefill resumes from the shipped blocks instead of recomputing them.
The warm/cold classification still probes the destination *before* the
import — it records what the handoff cost (shipping vs. nothing), and
keeps the ``serve.fleet.kv_handoff_*`` counter semantics of PR 10.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from ..obs.events import NULL_EVENT_LOG, REQUEST
from ..obs.telemetry import get_registry, labelled

if False:  # type-hint names only — the runtime imports are lazy because
    # serve/__init__ imports router which imports THIS module: a
    # top-level serve import here deadlocks whichever package the user
    # imports first (fleet-first and serve-first must both work)
    from ..serve.engine import ServeEngine  # noqa: F401
    from ..serve.queue import Request, RequestQueue, Response  # noqa: F401

__all__ = ["FleetController", "ReplicaTransport", "InProcessTransport",
           "Replica", "ReplicaHealth", "RouterPolicy", "TransportError",
           "HEALTHY", "SUSPECT", "WEDGED", "DRAINING", "RETIRED",
           "RETRYABLE_REASONS"]

HEALTHY = "healthy"
SUSPECT = "suspect"
WEDGED = "wedged"
DRAINING = "draining"
RETIRED = "retired"
STATES = (HEALTHY, SUSPECT, WEDGED, DRAINING, RETIRED)
_STATE_CODE = {s: i for i, s in enumerate(STATES)}

# Engine finish_reasons the controller may retry on another replica;
# every other terminal outcome is delivered as-is.
RETRYABLE_REASONS = ("backend_error", "stuck")


class TransportError(RuntimeError):
    """The transport to a replica died (socket drop, child crash,
    heartbeat loss). Raised by transport methods; the controller
    responds by reclaiming every request in flight on that replica and
    retiring it — the replica itself may be perfectly healthy, but
    unreachable is indistinguishable from dead."""


@dataclasses.dataclass
class ReplicaHealth:
    """One replica's health signals, as the control plane sees them.
    For an in-process replica these are live reads of the engine's
    watchdog surface; for a process replica they are the most recent
    heartbeat payload — the same fields, surviving the IPC boundary.
    ``heartbeat_age_s`` is 0.0 in-process (every read is fresh)."""

    slow_streak: int = 0
    miss_ewma: float = 0.0
    stuck_slots: int = 0
    consecutive_decode_errors: int = 0
    heartbeat_age_s: float = 0.0
    alive: bool = True


@dataclasses.dataclass
class RouterPolicy:
    """Fleet policy knobs. Defaults are deliberately conservative —
    quick to stop placing on a sick replica (SUSPECT is cheap: work
    just goes elsewhere), slow to wedge (WEDGED is one-way).

    ``placement`` — ``least_loaded`` picks the replica with the fewest
    queued+live requests (ties: lowest index); ``session`` pins each
    ``session`` key to its first replica while that replica is HEALTHY
    (KV-cache/prefix locality for multi-turn traffic) and falls back to
    least-loaded — remapping the session — when it isn't.

    ``retry_budget`` — max *placements* per request (``Request.attempts``
    is the ledger); a retryable failure at ``attempts >= retry_budget``
    is terminal. ``backoff_base_s``/``backoff_max_s`` shape the parked
    delay ``min(base * 2^(attempts-1), max)``; base 0 retries on the
    next tick (what deterministic fake-clock tests want — a parked
    request is only eligible once the queue clock passes its delay).

    SUSPECT triggers: ``suspect_slow_streak`` consecutive over-budget
    ticks (watchdog), any decode error, any retryable failure this
    tick, or ``suspect_miss_ewma`` (None disables the EWMA trigger).
    ``recover_healthy_ticks`` clean ticks clear SUSPECT. WEDGE
    triggers: ``wedge_slow_streak`` consecutive slow ticks,
    ``wedge_decode_errors`` consecutive decode errors (keep it below
    the engine's ``decode_error_limit``, which resets the streak), or
    ``wedge_error_ticks`` *cumulative* ticks that produced retryable
    failures (catches prefill-side death, where no decode streak ever
    forms). ``heartbeat_timeout_s`` (None disables) wedges a replica
    whose health snapshot is older than this — the IPC analog of a
    slow streak: an unreachable replica must not hold its queue.

    Lifecycle: ``spawn_depth``/``spawn_sustain_ticks``/``max_replicas``
    gate the spawn hook; ``retire_idle_ticks``/``min_replicas`` gate
    idle retirement (None disables).
    """

    placement: str = "least_loaded"
    # prefix placement + hot replication (paged pools only).
    # ``placement="prefix"`` scores candidates by matched-prefix depth x
    # occupancy headroom from each replica's advertised prefix
    # directory, falling back to session pin / least-loaded when nothing
    # matches. ``kv_hot_refs`` (None disables) proactively replicates
    # prefix chains shared by that many live slots to the
    # least-occupied sibling lacking them, via the same
    # export/import path a session remap uses; at most
    # ``kv_replicate_max_per_tick`` ships per tick.
    kv_hot_refs: Optional[int] = None
    kv_replicate_max_per_tick: int = 1
    retry_budget: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    suspect_slow_streak: int = 2
    suspect_miss_ewma: Optional[float] = None
    recover_healthy_ticks: int = 3
    wedge_slow_streak: int = 6
    wedge_decode_errors: int = 2
    wedge_error_ticks: int = 3
    heartbeat_timeout_s: Optional[float] = None
    spawn_depth: Optional[int] = None
    spawn_sustain_ticks: int = 10
    max_replicas: int = 8
    retire_idle_ticks: Optional[int] = None
    min_replicas: int = 1

    def __post_init__(self):
        if self.placement not in ("least_loaded", "session", "prefix"):
            raise ValueError(
                f"placement must be least_loaded|session|prefix, got "
                f"{self.placement!r}")
        if self.kv_hot_refs is not None and self.kv_hot_refs < 2:
            raise ValueError(
                f"kv_hot_refs must be >= 2 (a block one slot holds is "
                f"not hot) or None, got {self.kv_hot_refs}")
        if self.kv_replicate_max_per_tick < 1:
            raise ValueError(
                f"kv_replicate_max_per_tick must be >= 1, got "
                f"{self.kv_replicate_max_per_tick}")
        if self.retry_budget < 1:
            raise ValueError(
                f"retry_budget must be >= 1, got {self.retry_budget}")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff seconds must be >= 0")
        if self.heartbeat_timeout_s is not None \
                and self.heartbeat_timeout_s <= 0:
            raise ValueError("heartbeat_timeout_s must be > 0 or None")
        for fld in ("suspect_slow_streak", "recover_healthy_ticks",
                    "wedge_slow_streak", "wedge_decode_errors",
                    "wedge_error_ticks", "spawn_sustain_ticks",
                    "max_replicas", "min_replicas"):
            if getattr(self, fld) < 1:
                raise ValueError(f"{fld} must be >= 1")


# ---------------------------------------------------------------------------
# the transport interface


class ReplicaTransport:
    """What the control plane needs from one replica — nothing more.

    Implementations: :class:`InProcessTransport` (an engine in this
    process), :class:`~.proc.ProcessReplicaTransport` (a real OS
    process on the wire). Any method may raise :class:`TransportError`
    when the replica becomes unreachable; the controller reclaims and
    retires.

    ``rpc_inflight``/``rpc_retries`` are wire-level telemetry
    (0 in-process); they surface through the per-replica labelled
    gauges the controller exports every tick.

    ``obs_tokens_out``/``obs_responses_out`` are the
    delivery-synchronized per-replica counters the fleet observer sums:
    every transport bumps them at the exact moment a terminal response
    crosses into the control plane (``poll`` in-process, response-frame
    accept on the wire), so the per-replica sums reconcile with the
    parent-observed delivered totals even when a replica is SIGKILLed
    between two telemetry ships. ``obs_view()`` returns the shipped
    telemetry view ``(registry, age_s, seq, events)`` for transports
    that receive obs frames, None for transports the observer reads
    directly (in-process).
    """

    rpc_inflight: int = 0
    rpc_retries: int = 0
    obs_tokens_out: int = 0
    obs_responses_out: int = 0

    def obs_view(self):
        """Shipped-telemetry view ``(registry, age_s, seq, events)`` or
        None when this transport's replica is readable in-process."""
        return None

    # -- work ------------------------------------------------------------
    def place(self, req: Request) -> None:
        """Admit an existing request (increments ``req.attempts``).
        Raises like ``ServeEngine.place``: ``EngineDraining``,
        ``ValueError``, ``QueueFull`` — or :class:`TransportError`."""
        raise NotImplementedError

    def poll(self) -> List[Response]:
        """Advance the replica if this transport drives it (serial
        in-process mode) and return the terminal responses that
        finished since the last poll."""
        raise NotImplementedError

    def evict_queued(self) -> List[Union[Request, int]]:
        """Remove and return the replica's queued (not live) requests —
        as :class:`Request` objects when the transport holds them, or
        as request ids the controller resolves against its ledger."""
        raise NotImplementedError

    def cancel(self, request_id: int) -> bool:
        raise NotImplementedError

    def salvage(self) -> List[Response]:
        """Terminal responses already accepted on this side of the wire
        but never drained by a ``poll`` — returned WITHOUT a liveness
        check, so the drop path can read them after the wire is dead.
        Transports that count ``obs_tokens_out`` at frame-accept time
        (the process transport) MUST implement this: those tokens
        already crossed into the control plane, so re-running their
        requests on another replica would both waste a second decode
        and break the delivered-token reconciliation. Transports that
        count at drain time may return ``[]`` (the default) — their
        buffered work is uncounted and safe to retry."""
        return []

    # -- lifecycle -------------------------------------------------------
    def drain(self) -> None:
        raise NotImplementedError

    @property
    def drained(self) -> bool:
        raise NotImplementedError

    @property
    def idle(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        """Release transport resources (kill threads/processes)."""

    # -- placement surface ----------------------------------------------
    @property
    def queue_depth(self) -> int:
        raise NotImplementedError

    @property
    def queue_capacity(self) -> int:
        raise NotImplementedError

    @property
    def live_slots(self) -> int:
        raise NotImplementedError

    # -- admission validation -------------------------------------------
    def validate(self, prompt_len: int, max_new_tokens: int) -> None:
        raise NotImplementedError

    @property
    def default_max_new_tokens(self) -> int:
        raise NotImplementedError

    # -- health ----------------------------------------------------------
    def health(self) -> ReplicaHealth:
        raise NotImplementedError

    # -- KV handoff (paged pools only; every hook may no-op) -------------
    def export_prefix(self, prompt: Sequence[int]) -> Optional[dict]:
        """Serialize the cached shared-prefix blocks covering
        ``prompt`` (None when the backend has no pool / no hits)."""
        return None

    def import_prefix(self, payload: dict) -> int:
        """Seat an exported payload into this replica's pool; returns
        blocks seated (0 when unsupported)."""
        return 0

    def invalidate_prefix(self, prompt: Sequence[int]) -> int:
        """Drop this replica's cached prefix entries for ``prompt``;
        returns entries invalidated."""
        return 0

    def cached_prefix_blocks(self, prompt: Sequence[int]) -> int:
        """Leading full prompt blocks already cached here (the
        warm-handoff probe)."""
        return 0

    def prefix_directory(self) -> Optional[dict]:
        """This replica's advertised KV residency: ``{"block_size",
        "digests", "occupancy", "blocks_free", "blocks_total"}`` (the
        pool's ``prefix_digest_summary``), or None when the replica has
        no paged pool / the directory hasn't arrived yet. Process
        replicas ship it on the heartbeat cadence — it may be a beat
        stale, which placement tolerates (a miss just means a cold
        prefill)."""
        return None

    def hot_prefixes(self, min_refs: int) -> List[dict]:
        """Prefix chains shared by >= ``min_refs`` live slots, each as
        ``{"digest", "refs", "depth", "tokens"}`` with the full token
        chain — the proactive-replication feed."""
        return []


class InProcessTransport(ReplicaTransport):
    """One :class:`~..serve.engine.ServeEngine` behind the transport
    interface.

    Serial mode (default) is the PR 7 contract verbatim: the controller
    calls ``poll()`` once per fleet tick and that call runs
    ``engine.tick()`` — single-threaded, deterministic, what the pinned
    router tests drive with a fake clock.

    ``async_tick=True`` starts a daemon thread that ticks the engine
    whenever it has work; ``poll()`` just drains the finished-response
    buffer. Every engine call (tick/place/evict/drain) is serialized
    under one per-replica lock, so the engine itself stays
    single-threaded — the thread merely moves WHOSE loop runs it. A
    wedged or slow replica then blocks only its own thread.
    """

    def __init__(self, engine: ServeEngine, *, async_tick: bool = False,
                 tick_interval_s: float = 0.0,
                 role: Optional[str] = None):
        self.engine = engine
        # phase role for disaggregated placement (fleet/disagg.py):
        # defaults to the engine's own operating phase so an engine
        # built prefill-only/decode-only advertises itself correctly
        self.role = role if role is not None \
            else getattr(engine, "phase", "mixed")
        self.async_tick = bool(async_tick)
        self._lock = threading.Lock()
        self._buffer: "deque[Response]" = deque()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tick_interval_s = tick_interval_s
        if self.async_tick:
            self._thread = threading.Thread(
                target=self._tick_loop, name="fleet-replica-tick",
                daemon=True)
            self._thread.start()

    # -- async tick loop -------------------------------------------------

    def _tick_loop(self) -> None:
        while not self._stop.is_set():
            did_work = False
            with self._lock:
                eng = self.engine
                if not eng.idle or (eng.draining and not eng.drained):
                    self._buffer.extend(eng.tick())
                    did_work = True
            if not did_work:
                time.sleep(0.001)
            elif self._tick_interval_s:
                time.sleep(self._tick_interval_s)

    # -- work ------------------------------------------------------------

    def place(self, req: Request) -> None:
        with self._lock:
            self.engine.place(req)

    def poll(self) -> List[Response]:
        if self.async_tick:
            out = []
            while self._buffer:
                out.append(self._buffer.popleft())
        else:
            out = self.engine.tick()
        for resp in out:
            self.obs_tokens_out += len(resp.tokens)
            self.obs_responses_out += 1
        return out

    def evict_queued(self) -> List[Request]:
        with self._lock:
            return self.engine.evict_queued()

    def cancel(self, request_id: int) -> bool:
        return self.engine.cancel(request_id)

    # -- lifecycle -------------------------------------------------------

    def drain(self) -> None:
        with self._lock:
            self.engine.drain()

    @property
    def drained(self) -> bool:
        if self.async_tick:
            with self._lock:
                return self.engine.drained and not self._buffer
        return self.engine.drained

    @property
    def idle(self) -> bool:
        # pending async responses still count as work for the fleet —
        # and the async read must hold the tick lock: mid-tick the
        # engine can look idle (last slot retired) BEFORE the response
        # reaches the buffer, and an unlocked read of that instant
        # would let the controller conclude the fleet is done
        if self.async_tick:
            with self._lock:
                return self.engine.idle and not self._buffer
        return self.engine.idle

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None

    # -- placement surface ----------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self.engine.queue.depth

    @property
    def queue_capacity(self) -> int:
        return self.engine.queue.capacity

    @property
    def live_slots(self) -> int:
        return self.engine.live_slots

    # -- admission validation -------------------------------------------

    def validate(self, prompt_len: int, max_new_tokens: int) -> None:
        self.engine.backend.validate(prompt_len, max_new_tokens)

    @property
    def default_max_new_tokens(self) -> int:
        return self.engine.backend.gen.max_new_tokens

    # -- health ----------------------------------------------------------

    def health(self) -> ReplicaHealth:
        wd = self.engine.watchdog
        return ReplicaHealth(
            slow_streak=wd.slow_streak if wd is not None else 0,
            miss_ewma=wd.miss_ewma if wd is not None else 0.0,
            stuck_slots=wd.stuck_slots if wd is not None else 0,
            consecutive_decode_errors=(
                self.engine.consecutive_decode_errors),
            heartbeat_age_s=0.0, alive=True)

    # -- KV handoff ------------------------------------------------------

    def export_prefix(self, prompt: Sequence[int]) -> Optional[dict]:
        exp = getattr(self.engine.backend, "export_prefix_payload", None)
        if exp is None:
            return None
        # in-process: exact bytes (codec="raw"), no lossy serialization.
        # Must hold the tick lock: the async tick thread's decode step
        # DONATES the pool buffers, and an export racing it reads a
        # deleted buffer (the disagg controller exports from replicas
        # that are still actively prefilling).
        with self._lock:
            return exp(prompt, codec="raw")

    def import_prefix(self, payload: dict) -> int:
        imp = getattr(self.engine.backend, "import_prefix_payload", None)
        if imp is None:
            return 0
        with self._lock:
            return imp(payload)

    def invalidate_prefix(self, prompt: Sequence[int]) -> int:
        pool = getattr(self.engine.backend, "pool", None)
        if pool is None:
            return 0
        with self._lock:
            return pool.invalidate(pool.prefix_hashes(prompt))

    def cached_prefix_blocks(self, prompt: Sequence[int]) -> int:
        pool = getattr(self.engine.backend, "pool", None)
        if pool is None:
            return 0
        with self._lock:
            return pool.cached_prefix_blocks(prompt)

    def prefix_directory(self) -> Optional[dict]:
        pool = getattr(self.engine.backend, "pool", None)
        if pool is None:
            return None
        return pool.prefix_digest_summary()

    def hot_prefixes(self, min_refs: int) -> List[dict]:
        pool = getattr(self.engine.backend, "pool", None)
        if pool is None:
            return []
        return pool.hot_prefixes(min_refs)


# ---------------------------------------------------------------------------
# replica record


class Replica:
    """Controller-side record of one replica: health state plus the
    hysteresis counters the state machine runs on. ``engine`` is the
    in-process convenience accessor (None for a process replica)."""

    __slots__ = ("index", "transport", "state", "healthy_streak",
                 "idle_ticks", "error_ticks", "had_error_this_tick")

    def __init__(self, index: int, transport: ReplicaTransport):
        self.index = index
        self.transport = transport
        self.state = HEALTHY
        self.healthy_streak = 0
        self.idle_ticks = 0
        self.error_ticks = 0          # cumulative ticks with retryable fails
        self.had_error_this_tick = False

    @property
    def engine(self):
        return getattr(self.transport, "engine", None)

    @property
    def role(self) -> str:
        """The replica's phase role (``prefill``/``decode``/``mixed``),
        as advertised by its transport. A transport that predates roles
        reads as ``mixed`` — the serve-both-phases default."""
        return getattr(self.transport, "role", "mixed")

    @property
    def load(self) -> int:
        return self.transport.queue_depth + self.transport.live_slots

    def __repr__(self) -> str:
        return (f"Replica({self.index}, state={self.state}, "
                f"load={self.load})")


# ---------------------------------------------------------------------------
# the controller


class FleetController:
    """Shard one front :class:`~..serve.queue.RequestQueue` across N
    replica transports with health-gated failover.

    The surface mirrors :class:`~..serve.engine.ServeEngine` — ``submit``
    / ``tick`` / ``cancel`` / ``response`` / ``drain`` / ``idle`` /
    ``run_until_idle`` — so drivers (``apps/serve.py``) swap one for
    the other without restructuring their loop. ``spawn_fn`` (if given)
    builds one more transport on demand for the spawn hook.
    """

    def __init__(self, transports: Sequence[ReplicaTransport],
                 queue: Optional[RequestQueue] = None, *,
                 policy: RouterPolicy = RouterPolicy(),
                 spawn_fn: Optional[Callable[[], ReplicaTransport]] = None,
                 event_log=None,
                 clock: Optional[Callable[[], float]] = None,
                 journal=None):
        transports = list(transports)
        if not transports:
            raise ValueError(
                "the fleet needs at least one replica transport")
        if queue is None:
            from ..serve.queue import RequestQueue
            queue = RequestQueue(clock=clock or time.monotonic)
        elif clock is not None and clock is not queue.clock:
            raise ValueError(
                "pass the clock on the queue (the fleet adopts "
                "queue.clock)")
        self.queue = queue
        self.clock = queue.clock
        self.policy = policy
        self.spawn_fn = spawn_fn
        self.journal = journal
        self.events = event_log if event_log is not None else NULL_EVENT_LOG
        self.replicas: List[Replica] = []
        for tr in transports:
            self._add_replica(tr)
        self._responses: Dict[int, Response] = {}
        self._tracked: Dict[int, Request] = {}
        self._parked: List[Tuple[float, Request]] = []
        self._session_of: Dict[int, str] = {}
        self._session_map: Dict[str, int] = {}
        self._placed_on: Dict[int, int] = {}
        self._kv_replicated: Dict[str, set] = {}
        self._pending_out: List[Response] = []
        self._tick_index = 0
        self._depth_streak = 0
        self._draining = False

    # -- construction helpers ----------------------------------------------

    def _add_replica(self, transport: ReplicaTransport) -> Replica:
        rep = Replica(len(self.replicas), transport)
        self.replicas.append(rep)
        return rep

    # -- front door --------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None, seed: int = 0,
               priority: int = 0, timeout_s: Optional[float] = None,
               session: Optional[str] = None) -> Request:
        """Validate + enqueue at the fleet front door. Raises
        ``ValueError`` on an unservable request,
        :class:`~..serve.engine.EngineDraining` after :meth:`drain`, and
        :class:`~..serve.queue.QueueFull` when the front queue is at
        capacity — which is exactly what happens when every replica is
        SUSPECT or worse: placement stops, the front fills, callers feel
        backpressure instead of silent loss."""
        from ..serve.engine import EngineDraining
        from ..serve.queue import QueueFull
        reg = get_registry()
        if self._draining:
            raise EngineDraining(
                "fleet is draining: live requests are finishing and no "
                "new work is admitted")
        tr = self.replicas[0].transport
        if max_new_tokens is None:
            max_new_tokens = tr.default_max_new_tokens
        tr.validate(len(prompt), max_new_tokens)
        try:
            req = self.queue.submit(prompt, max_new_tokens=max_new_tokens,
                                    seed=seed, priority=priority,
                                    timeout_s=timeout_s)
        except QueueFull:
            reg.counter("serve.fleet.rejected").inc()
            raise
        if self.journal is not None:
            # journaled BEFORE the request becomes placeable: a crash
            # from here on replays it from the WAL
            self.journal.append(
                "submit", request=req.id, prompt=list(req.prompt),
                max_new_tokens=req.max_new_tokens, seed=req.seed,
                priority=req.priority, trace=req.trace_id,
                session=None if session is None else str(session),
                remaining_s=(None if req.deadline is None
                             else req.deadline - self.clock()))
        self._tracked[req.id] = req
        if session is not None:
            self._session_of[req.id] = str(session)
        reg.counter("serve.fleet.submitted").inc()
        reg.gauge("serve.fleet.front_depth").set(self.queue.depth)
        self.events.event(REQUEST, request=req.id, trace=req.trace_id,
                          stage="queued", prompt_len=len(req.prompt),
                          priority=req.priority, session=session)
        return req

    def cancel(self, request_id: int) -> bool:
        """Mark a live request cancelled wherever it currently sits —
        front queue, parked for retry, a replica's queue, or a running
        slot. One flag flip on the shared :class:`~..serve.queue.Request`;
        whichever sweep sees it first emits the single terminal
        ``cancelled`` response. False for unknown/terminal ids."""
        req = self._tracked.get(request_id)
        if req is None:
            return False
        req.cancelled = True
        # a process replica holds a COPY of the request across the wire:
        # forward the flag so the remote sweep sees it too
        idx = self._placed_on.get(request_id)
        if idx is not None:
            try:
                self.replicas[idx].transport.cancel(request_id)
            except TransportError:
                pass  # drop recovery reclaims it next tick
        return True

    def response(self, request_id: int) -> Optional[Response]:
        return self._responses.get(request_id)

    # -- drain / status ----------------------------------------------------

    def drain(self) -> None:
        """Fleet-wide graceful shutdown: ``submit`` starts raising, the
        next tick sheds front-queued and parked work
        (``finish_reason="drain"``) and every replica drains its live
        slots. Idempotent."""
        if not self._draining:
            self._draining = True
            self.events.event("resilience", action="fleet_drain",
                              front=self.queue.depth,
                              parked=len(self._parked))
            for rep in self.replicas:
                if rep.state != RETIRED:
                    try:
                        rep.transport.drain()
                    except TransportError:
                        pass

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drained(self) -> bool:
        return self._draining and self.idle

    @property
    def idle(self) -> bool:
        # undrained salvaged responses (_pending_out) are still work:
        # a caller that gates its tick loop on idle must not conclude
        # the fleet is done while deliveries sit in the hand-off buffer
        return (self.queue.depth == 0 and not self._parked
                and not self._pending_out
                and all(r.state == RETIRED or r.transport.idle
                        for r in self.replicas))

    def counts(self) -> Dict[str, int]:
        """Replica count per health state (``{state: n}``)."""
        out = {s: 0 for s in STATES}
        for rep in self.replicas:
            out[rep.state] += 1
        return out

    def close(self) -> None:
        """Release every transport (threads, child processes)."""
        for rep in self.replicas:
            try:
                rep.transport.close()
            except Exception:
                pass

    # -- crash recovery (restart from the journal) -------------------------

    @classmethod
    def from_journal(cls, state, transports: Sequence[ReplicaTransport],
                     queue: Optional[RequestQueue] = None, *,
                     journal=None, policy: RouterPolicy = RouterPolicy(),
                     spawn_fn=None, event_log=None,
                     clock: Optional[Callable[[], float]] = None
                     ) -> "FleetController":
        """Rebuild a controller after a crash: ``state`` is the
        replayed WAL (:meth:`~.journal.RequestJournal.recover`) and
        ``transports`` the re-dialed surviving children (rejoin-mode
        :class:`~.proc.ProcessReplicaTransport`, index-aligned with the
        journal's replica records). The exactly-once ledger, retry
        parks and phase tags come back from the journal; placements are
        reconciled against what each child actually still holds —
        still live there → adopted in place, finished during the outage
        → its replayed response salvaged and delivered, gone → parked
        for immediate re-placement. Pass a fresh ``journal`` on the
        same path to keep the WAL growing through the new life."""
        ctl = cls(transports, queue, policy=policy, spawn_fn=spawn_fn,
                  event_log=event_log, clock=clock, journal=journal)
        ctl._restore(state)
        return ctl

    def _restore(self, state) -> None:
        import itertools
        from ..serve.queue import Request, Response
        reg = get_registry()
        now = self.clock()
        # never reuse a journaled id: the front queue's sequence resumes
        # past everything the previous life handed out
        self.queue._seq = itertools.count(state.max_request_id + 1)
        # terminal stubs: ids the previous life already answered. A
        # replica replaying one of their responses — or a recovered
        # placement racing to finish one — must still trip the
        # duplicate-delivery raise, so the ledger gets a stub per id.
        for rid, rec in state.terminal.items():
            self._responses[rid] = Response(
                request_id=rid, tokens=[], status=rec.get("status", "ok"),
                finish_reason=rec.get("finish_reason", "eos"),
                prompt_len=0, ttft=None, latency=0.0)
        if state.clean:
            # the log ends with clean_shutdown: nothing was in flight
            self.events.event("resilience", action="controller_restart",
                              clean=True, terminal=len(state.terminal))
            return
        # what does each surviving child still hold? (rejoin-mode
        # transports answer over the wire; anything else has no state)
        live_ids: Dict[int, set] = {}
        buffered: Dict[int, set] = {}
        for rep in self.replicas:
            tr = rep.transport
            fn = getattr(tr, "remote_request_ids", None)
            if fn is not None:
                try:
                    live_ids[rep.index] = set(fn())
                except TransportError:
                    live_ids[rep.index] = set()
            fn = getattr(tr, "orphan_response_ids", None)
            if fn is not None:
                buffered[rep.index] = set(fn())
        orphans = 0
        adopted = 0
        for rid in state.orphans:
            rec = state.requests[rid]
            req = Request(
                id=rid, prompt=list(rec["prompt"]),
                max_new_tokens=int(rec["max_new_tokens"]),
                seed=int(rec.get("seed", 0)),
                priority=int(rec.get("priority", 0)),
                deadline=(None if rec.get("remaining_s") is None
                          else now + float(rec["remaining_s"])),
                submitted_at=now, trace_id=rec.get("trace"))
            req.attempts = state.attempts.get(rid, 0)
            orphans += 1
            self._tracked[rid] = req
            sess = rec.get("session")
            if sess is not None:
                self._session_of[rid] = sess
            self._restore_phase(req, state)
            target = state.placed_on.get(rid)
            if target is not None and target < len(self.replicas) \
                    and (rid in live_ids.get(target, ())
                         or rid in buffered.get(target, ())):
                adopt = getattr(self.replicas[target].transport,
                                "adopt", None)
                if adopt is not None:
                    adopt(req)
                    self._placed_on[rid] = target
                    adopted += 1
                    continue
            # not live anywhere we can see: park, eligible immediately
            self._parked.append((now, req))
        # responses the children replayed for ids that were NOT
        # adopted: journaled terminals are duplicates (drop), tracked
        # orphans are work that finished during the outage (salvage)
        salvaged = 0
        for rep in self.replicas:
            seal = getattr(rep.transport, "seal_rejoin", None)
            if seal is None:
                continue
            for resp in seal():
                if resp.request_id in self._responses:
                    continue
                if resp.request_id in self._tracked:
                    out = self._salvage(rep, resp)
                    if out is not None:
                        self._pending_out.append(out)
                        salvaged += 1
        self._parked = [(t, r) for t, r in self._parked
                        if r.id not in self._responses]
        reg.counter("serve.fleet.recovered_orphans").inc(orphans)
        reg.counter("serve.fleet.recovered_adopted").inc(adopted)
        if salvaged:
            reg.counter("serve.fleet.salvaged").inc(salvaged)
        self.events.event("resilience", action="controller_restart",
                          clean=False, terminal=len(state.terminal),
                          orphans=orphans, adopted=adopted,
                          salvaged=salvaged, parked=len(self._parked))

    def _restore_phase(self, req: Request, state) -> None:
        """Phase-tag hook for recovery — the base controller has no
        phases. :class:`~.disagg.DisaggController` overrides."""

    def _salvage(self, rep: Replica, resp: Response):
        """One response ``rep``'s child replayed for a tracked orphan:
        for the base controller every replica is terminal-producing,
        so it IS the finished work of the outage — deliver it.
        :class:`~.disagg.DisaggController` overrides to tell replayed
        shadow frames apart from genuine decode terminals."""
        return self._deliver(resp)

    # -- delivery (the exactly-once ledger) --------------------------------

    def _deliver(self, resp: Response) -> Optional[Response]:
        """Record a terminal response in the exactly-once ledger and
        return it. Subclasses may return None to CONSUME a response
        instead of delivering it (the disaggregated controller swallows
        the prefill phase's one-token terminal and re-enters the
        request for its decode phase) — every caller that surfaces
        responses must tolerate None. The base implementation never
        returns None, and never intercepts the ``_finish_unplaced``
        records (their status is never ``ok``)."""
        if resp.request_id in self._responses:
            raise RuntimeError(
                f"duplicate terminal response for request "
                f"{resp.request_id} (exactly-once delivery violated)")
        if self.journal is not None:
            # the exactly-once hinge: durable before the ledger record,
            # so a restart can never answer this id a second time
            self.journal.append(
                "deliver", request=resp.request_id, status=resp.status,
                finish_reason=resp.finish_reason, tokens=len(resp.tokens))
        self._responses[resp.request_id] = resp
        req = self._tracked.pop(resp.request_id, None)
        self._session_of.pop(resp.request_id, None)
        placed_on = self._placed_on.pop(resp.request_id, None)
        self.queue.forget(resp.request_id)
        reg = get_registry()
        reg.counter("serve.fleet.delivered").inc()
        reg.counter("serve.fleet.delivered_tokens").inc(len(resp.tokens))
        if resp.status == "ok":
            reg.counter("serve.fleet.ok").inc()
        if req is not None and req.attempts > 1:
            reg.counter("serve.fleet.failed_over").inc()
        self.events.event(REQUEST, request=resp.request_id,
                          trace=getattr(req, "trace_id", None),
                          stage="delivered", status=resp.status,
                          finish_reason=resp.finish_reason,
                          tokens=len(resp.tokens), replica=placed_on,
                          attempts=getattr(req, "attempts", 0))
        return resp

    def _finish_unplaced(self, req: Request, status: str, reason: str,
                         now: float) -> Response:
        """Terminal record for a request that never (re)reached a
        replica: front-reaped, parked-reaped, shed on fleet drain, or
        retries exhausted."""
        from ..serve.queue import Response
        resp = Response(request_id=req.id, tokens=[], status=status,
                        finish_reason=reason, prompt_len=len(req.prompt),
                        ttft=None, latency=now - req.submitted_at)
        self.events.event(REQUEST, request=req.id, status=status,
                          finish_reason=reason, replica=None,
                          attempts=req.attempts, trace=req.trace_id,
                          stage="terminal")
        return self._deliver(resp)

    # -- retry parking -----------------------------------------------------

    def _as_requests(self,
                     items: Sequence[Union[Request, int]]) -> List[Request]:
        """Resolve a transport's evicted items — Request objects pass
        through; bare ids (a process replica holds only copies) map to
        the controller's tracked originals, which are authoritative for
        deadlines and attempts. Unknown/already-terminal ids drop."""
        from ..serve.queue import Request
        out: List[Request] = []
        for it in items:
            req = it if isinstance(it, Request) else self._tracked.get(it)
            if req is not None:
                out.append(req)
        return out

    def reclaim(self, requests: List[Request], now: float) -> List[Response]:
        """Re-absorb requests knocked off a replica — the ONE
        park-or-finish decision all recovery paths share (a wedged
        replica's evicted backlog, per-request retryable failures from
        a live tick, and a transport drop's in-flight set), so the
        exactly-once ledger has a single writer. Per request: cancelled
        or past its deadline → parked for the next sweep's terminal
        cancelled/timeout record; retry budget remaining → parked with
        exponential backoff; else ONE terminal ``retries_exhausted``
        error. Returns the terminal responses (already recorded in the
        ledger); parked requests surface through later ticks."""
        reg = get_registry()
        finished: List[Response] = []
        for req in requests:
            if req.cancelled or (req.deadline is not None
                                 and now >= req.deadline):
                # next tick's parked sweep emits the terminal
                # cancelled/timeout record
                self._parked.append((now, req))
            elif req.attempts < self.policy.retry_budget:
                self._park(req, now)
            else:
                reg.counter("serve.fleet.retries_exhausted").inc()
                finished.append(self._finish_unplaced(
                    req, "error", "retries_exhausted", now))
        return finished

    def _park(self, req: Request, now: float) -> None:
        p = self.policy
        delay = min(p.backoff_base_s * (2.0 ** max(req.attempts - 1, 0)),
                    p.backoff_max_s)
        if self.journal is not None:
            self.journal.append("park", request=req.id,
                                attempts=req.attempts, delay_s=delay)
        self._parked.append((now + delay, req))
        get_registry().counter("serve.fleet.retried").inc()
        self.events.event("resilience", action="retry_parked",
                          request=req.id, attempts=req.attempts,
                          delay_s=delay, trace=req.trace_id,
                          stage="retry_parked")

    # -- placement ---------------------------------------------------------

    def _placeable(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.state == HEALTHY
                and r.transport.queue_depth < r.transport.queue_capacity]

    def _role_filter(self, req: Request,
                     candidates: List[Replica]) -> List[Replica]:
        """Restrict placement candidates by the request's phase.

        A phase-tagged request (``req.phase`` set by the disaggregated
        controller) wants its role pool — ``prefill`` requests go to
        prefill replicas, ``decode`` requests to decode replicas — and
        falls back to mixed replicas when the wanted pool is empty or
        entirely sick. A phase-less request only ever lands on mixed
        replicas: a prefill-only engine would reject its
        ``max_new_tokens`` and a decode-only engine would refuse to
        prefill it. In an all-mixed fleet (every deployment before
        disaggregation) this is the identity filter."""
        want = getattr(req, "phase", None)
        if want in ("prefill", "decode"):
            pool = [r for r in candidates if r.role == want]
            return pool or [r for r in candidates if r.role == "mixed"]
        return [r for r in candidates if r.role == "mixed"]

    def _choose(self, req: Request, candidates: List[Replica]) -> Replica:
        if self.policy.placement == "prefix":
            rep = self._choose_by_prefix(req, candidates)
            if rep is not None:
                return rep
        if self.policy.placement in ("session", "prefix"):
            sess = self._session_of.get(req.id)
            if sess is not None:
                home = self._session_map.get(sess)
                for rep in candidates:
                    if rep.index == home:
                        return rep
        return min(candidates, key=lambda r: (r.load, r.index))

    def _choose_by_prefix(self, req: Request,
                          candidates: List[Replica]) -> Optional[Replica]:
        """Score candidates by matched-prefix depth x occupancy
        headroom from their advertised directories: the request lands
        where its prefix already lives UNLESS that replica is nearly
        full (a deep match on a saturated pool would evict what it came
        for). None when no candidate matches anything — the caller
        falls back to session pin / least-loaded."""
        from ..serve.kvpool import prefix_hashes, prefix_match_depth
        best: Optional[Replica] = None
        best_key: Tuple[float, int, int] = (0.0, 0, 0)
        for rep in candidates:
            try:
                d = rep.transport.prefix_directory()
            except TransportError:
                continue
            if not d or not d.get("digests") or not d.get("block_size"):
                continue
            depth = prefix_match_depth(
                prefix_hashes(req.prompt, int(d["block_size"])),
                set(d["digests"]))
            if depth == 0:
                continue
            total = max(1, int(d.get("blocks_total", 1)))
            headroom = max(0.05, int(d.get("blocks_free", 0)) / total)
            key = (depth * headroom, -rep.load, -rep.index)
            if key > best_key:
                best, best_key = rep, key
        if best is not None:
            get_registry().counter("serve.fleet.prefix_placements").inc()
        return best

    def _kv_handoff(self, req: Request, sess: str, old_idx: int,
                    new_rep: Replica) -> None:
        """Session remap off its home replica: actually move the
        session's shared-prefix KV. Order matters and each step keeps
        the PR 10 counter semantics:

        1. probe the NEW home (warm/cold classifies what this handoff
           costs — before the import, or shipping would mask itself);
        2. export the cached prefix blocks from the OLD home (raw bytes
           in-process, int8-serialized across a process boundary);
        3. seat them into the new home's pool (refcount-0 cached
           entries: the request's admission takes the refs);
        4. invalidate the old home (the conversation continues on the
           new home; a later remap BACK must re-prefill, not extend a
           stale prefix).

        Transports without a paged pool no-op every step — the hook
        then only moves counters, exactly the PR 7/10 behavior."""
        reg = get_registry()
        reg.counter("serve.fleet.kv_handoff_total").inc()
        old_tr = self.replicas[old_idx].transport
        new_tr = new_rep.transport
        warm = new_tr.cached_prefix_blocks(req.prompt)
        shipped = nbytes = 0
        if not warm:
            try:
                payload = old_tr.export_prefix(req.prompt)
            except TransportError:
                payload = None    # dead home: nothing to ship
            if payload is not None:
                nbytes = int(payload.get("nbytes", 0))
                try:
                    shipped = new_tr.import_prefix(payload)
                except TransportError:
                    shipped = 0
        if shipped:
            reg.counter("serve.fleet.kv_handoff_shipped").inc(shipped)
            reg.counter("serve.fleet.kv_handoff_bytes").inc(nbytes)
            reg.gauge(labelled("serve.fleet.handoff_bytes",
                               replica=new_rep.index)).set(nbytes)
        invalidated = 0
        try:
            invalidated = old_tr.invalidate_prefix(req.prompt)
        except TransportError:
            pass
        if invalidated:
            reg.counter(
                "serve.fleet.kv_handoff_invalidated").inc(invalidated)
        reg.counter("serve.fleet.kv_handoff_warm" if warm
                    else "serve.fleet.kv_handoff_cold").inc()
        self.events.event("resilience", action="kv_handoff",
                          request=req.id, session=sess,
                          from_replica=old_idx, to_replica=new_rep.index,
                          invalidated=invalidated, warm_blocks=warm,
                          shipped_blocks=shipped, bytes=nbytes,
                          trace=req.trace_id, stage="handoff",
                          attempts=req.attempts)

    def _replicate_hot_prefixes(self) -> None:
        """Push hot prefixes (refcount >= ``policy.kv_hot_refs``) to one
        sibling each, ahead of demand, through the same export/import
        path a session remap uses. A digest ships at most once per
        (digest, target) pair — ``_kv_replicated`` remembers what went
        where — and at most ``kv_replicate_max_per_tick`` exports run
        per tick so replication never starves placement."""
        reg = get_registry()
        budget = self.policy.kv_replicate_max_per_tick
        healthy = [r for r in self.replicas if r.state == HEALTHY]
        if len(healthy) < 2:
            return
        for src in healthy:
            if budget <= 0:
                return
            try:
                hot = src.transport.hot_prefixes(self.policy.kv_hot_refs)
            except TransportError:
                continue
            for entry in hot:
                if budget <= 0:
                    return
                digest = entry.get("digest")
                tokens = entry.get("tokens")
                if not digest or not tokens:
                    continue
                shipped_to = self._kv_replicated.setdefault(digest, set())
                sibling = None
                sib_free = -1.0
                for rep in healthy:
                    if rep is src or rep.index in shipped_to:
                        continue
                    try:
                        d = rep.transport.prefix_directory()
                    except TransportError:
                        continue
                    if d and digest in set(d.get("digests", ())):
                        shipped_to.add(rep.index)   # already resident
                        continue
                    free = (int(d.get("blocks_free", 0))
                            / max(1, int(d.get("blocks_total", 1)))
                            if d else 0.0)
                    if free > sib_free:
                        sibling, sib_free = rep, free
                if sibling is None:
                    continue
                budget -= 1
                try:
                    payload = src.transport.export_prefix(tokens)
                except TransportError:
                    continue
                if payload is None:
                    continue
                try:
                    seated = sibling.transport.import_prefix(payload)
                except TransportError:
                    continue
                shipped_to.add(sibling.index)
                if seated:
                    nbytes = int(payload.get("nbytes", 0))
                    reg.counter("serve.fleet.kv_replicated").inc(seated)
                    reg.counter("serve.fleet.kv_replicated_bytes").inc(
                        nbytes)
                    self.events.event(
                        "serve", action="kv_replicated",
                        digest=digest[:12], blocks=seated,
                        refs=entry.get("refs"),
                        from_replica=src.index,
                        to_replica=sibling.index, bytes=nbytes)

    def _try_place(self, req: Request, now: float) -> bool:
        candidates = self._role_filter(req, self._placeable())
        if not candidates:
            return False
        rep = self._choose(req, candidates)
        sess = self._session_of.get(req.id)
        if sess is not None:
            home = self._session_map.get(sess)
            if home is not None and home != rep.index:
                self._kv_handoff(req, sess, home, rep)
        if self.journal is not None:
            self.journal.append("place", request=req.id,
                                replica=rep.index,
                                attempts=req.attempts + 1)
        try:
            rep.transport.place(req)        # increments req.attempts
        except TransportError:
            self._transport_drop(rep, now)
            return False
        self._placed_on[req.id] = rep.index
        if sess is not None and rep.state == HEALTHY:
            self._session_map[sess] = rep.index
        self.events.event(REQUEST, request=req.id, trace=req.trace_id,
                          stage="placed", replica=rep.index,
                          attempts=req.attempts)
        return True

    # -- health state machine ----------------------------------------------

    def _inflight_on(self, rep: Replica) -> List[Request]:
        """Requests currently placed on this replica, per the
        controller's own ledger — the authoritative in-flight map when
        the transport can no longer be asked."""
        return [self._tracked[rid]
                for rid, idx in list(self._placed_on.items())
                if idx == rep.index and rid in self._tracked]

    def _transport_drop(self, rep: Replica, now: float) -> None:
        """The transport died (not necessarily the replica): reclaim
        everything in flight there from the controller ledger and
        retire the replica — exactly-once holds because the ledger
        lives here and the dead connection's frames are never read
        again. One-way, like a wedge, but with no drain (nothing can be
        asked to drain)."""
        if rep.state == RETIRED:
            return
        reg = get_registry()
        reg.counter("serve.fleet.transport_drops").inc()
        # Responses the wire delivered before it died but no poll ever
        # drained: deliver them. The work is done and (on the process
        # transport) their tokens are already in ``obs_tokens_out``, so
        # reclaiming those requests would run a second decode elsewhere
        # and leave counted-but-undelivered tokens breaking the
        # observer's reconciliation. Delivered BEFORE computing the
        # in-flight set so they drop out of ``_placed_on`` first.
        try:
            salvaged = rep.transport.salvage()
        except Exception:
            salvaged = []
        for resp in salvaged:
            out = self._deliver(resp)
            if out is not None:
                self._pending_out.append(out)
        if salvaged:
            reg.counter("serve.fleet.salvaged").inc(len(salvaged))
        inflight = self._inflight_on(rep)
        for req in inflight:
            self._placed_on.pop(req.id, None)
        self.events.event("resilience", action="transport_drop",
                          replica=rep.index, inflight=len(inflight),
                          salvaged=len(salvaged))
        rep.state = RETIRED
        reg.counter("serve.fleet.retired").inc()
        try:
            rep.transport.close()
        except Exception:
            pass
        self.reclaim(inflight, now)

    def _wedge(self, rep: Replica, reason: str, now: float) -> None:
        """WEDGED: reclaim the backlog intact, re-place or park it under
        the retry budget, and start draining the live slots. One-way."""
        rep.state = WEDGED
        get_registry().counter("serve.fleet.wedged").inc()
        try:
            evicted = self._as_requests(rep.transport.evict_queued())
        except TransportError:
            # the transport is gone too: the drop path reclaims the
            # whole in-flight set itself — reclaiming `evicted` here as
            # well would park every request TWICE and break the
            # exactly-once ledger with duplicate terminals
            self._transport_drop(rep, now)
            return
        self.events.event("resilience", action="replica_wedged",
                          replica=rep.index, reason=reason,
                          evicted=len(evicted))
        for req in evicted:
            self._placed_on.pop(req.id, None)
        # terminal responses land in the ledger; tick's delivered list
        # picks them up via response() like any mid-health-pass finish
        self.reclaim(evicted, now)
        if rep.state == WEDGED:          # transport still up: drain live
            try:
                rep.transport.drain()
                rep.state = DRAINING
            except TransportError:
                self._transport_drop(rep, now)

    def _update_health(self, rep: Replica, now: float) -> None:
        p = self.policy
        if rep.state == RETIRED:
            return
        if rep.state == DRAINING:
            try:
                if rep.transport.drained:
                    rep.state = RETIRED
                    get_registry().counter("serve.fleet.retired").inc()
                    self.events.event("resilience",
                                      action="replica_retired",
                                      replica=rep.index)
            except TransportError:
                self._transport_drop(rep, now)
            return

        try:
            h = rep.transport.health()
        except TransportError:
            self._transport_drop(rep, now)
            return
        if not h.alive or (p.heartbeat_timeout_s is not None
                           and h.heartbeat_age_s > p.heartbeat_timeout_s):
            self._wedge(rep, f"heartbeat lost (age="
                             f"{h.heartbeat_age_s:.3f}s)", now)
            return
        slow = h.slow_streak
        ewma = h.miss_ewma
        derr = h.consecutive_decode_errors
        if rep.had_error_this_tick:
            rep.error_ticks += 1

        if (slow >= p.wedge_slow_streak or derr >= p.wedge_decode_errors
                or rep.error_ticks >= p.wedge_error_ticks):
            self._wedge(rep, f"slow_streak={slow} decode_errors={derr} "
                             f"error_ticks={rep.error_ticks}", now)
            return

        bad = (slow >= p.suspect_slow_streak or derr > 0
               or rep.had_error_this_tick
               or (p.suspect_miss_ewma is not None
                   and ewma > p.suspect_miss_ewma))
        if rep.state == HEALTHY and bad:
            rep.state = SUSPECT
            rep.healthy_streak = 0
            get_registry().counter("serve.fleet.suspected").inc()
            self.events.event("resilience", action="replica_suspect",
                              replica=rep.index, slow_streak=slow,
                              decode_errors=derr, miss_ewma=ewma)
        elif rep.state == SUSPECT:
            if bad:
                rep.healthy_streak = 0
            else:
                rep.healthy_streak += 1
                if rep.healthy_streak >= p.recover_healthy_ticks:
                    rep.state = HEALTHY
                    rep.healthy_streak = 0
                    get_registry().counter("serve.fleet.recovered").inc()
                    self.events.event("resilience",
                                      action="replica_recovered",
                                      replica=rep.index)

    def _lifecycle(self, now: float) -> None:
        """Spawn on sustained front-queue depth; retire sustained-idle
        replicas (never below ``min_replicas`` placeable ones)."""
        p = self.policy
        active = [r for r in self.replicas if r.state in (HEALTHY, SUSPECT)]
        if p.spawn_depth is not None and self.spawn_fn is not None:
            if self.queue.depth >= p.spawn_depth:
                self._depth_streak += 1
            else:
                self._depth_streak = 0
            if self._depth_streak >= p.spawn_sustain_ticks \
                    and len(active) < p.max_replicas:
                rep = self._add_replica(self.spawn_fn())
                self._depth_streak = 0
                get_registry().counter("serve.fleet.spawned").inc()
                self.events.event("resilience", action="replica_spawned",
                                  replica=rep.index,
                                  front_depth=self.queue.depth)
        if p.retire_idle_ticks is None:
            return
        for rep in self.replicas:
            if rep.state != HEALTHY:
                continue
            if rep.transport.idle and self.queue.depth == 0 \
                    and not self._parked:
                rep.idle_ticks += 1
            else:
                rep.idle_ticks = 0
            active = [r for r in self.replicas
                      if r.state in (HEALTHY, SUSPECT)]
            if rep.idle_ticks >= p.retire_idle_ticks \
                    and len(active) > p.min_replicas:
                rep.transport.drain()
                rep.state = DRAINING
                rep.idle_ticks = 0
                get_registry().counter("serve.fleet.idle_retired").inc()
                self.events.event("resilience",
                                  action="replica_idle_retired",
                                  replica=rep.index)

    # -- the fleet tick ----------------------------------------------------

    def tick(self) -> List[Response]:
        """One fleet scheduling round: sweep the front/parked sets,
        advance every replica's health machine, place onto HEALTHY
        replicas, poll the replicas (serial in-process transports tick
        here; async/process replicas tick themselves and this just
        drains), then deliver-or-retry their terminal responses.
        Returns the responses DELIVERED this tick (retried failures are
        not delivered — they park)."""
        reg = get_registry()
        now = self.clock()
        tick_idx = self._tick_index
        delivered: List[Response] = []

        # 0) fleet drain — push back everything not yet on a replica
        if self._draining:
            for req in self.queue.evict_all():
                delivered.append(
                    self._finish_unplaced(req, "shed", "drain", now))
            for _, req in self._parked:
                delivered.append(
                    self._finish_unplaced(req, "shed", "drain", now))
            self._parked = []

        # 1) front + parked sweeps — deaths that never cost a replica
        for req, reason in self.queue.reap(now):
            status = "cancelled" if reason == "cancelled" else "timeout"
            delivered.append(
                self._finish_unplaced(req, status, reason, now))
        still = []
        for eligible_at, req in self._parked:
            if req.cancelled:
                delivered.append(
                    self._finish_unplaced(req, "cancelled", "cancelled",
                                          now))
            elif req.deadline is not None and now >= req.deadline:
                delivered.append(
                    self._finish_unplaced(req, "timeout", "deadline", now))
            else:
                still.append((eligible_at, req))
        self._parked = still

        # 2) health transitions + lifecycle (uses last tick's signals)
        for rep in self.replicas:
            self._update_health(rep, now)
            rep.had_error_this_tick = False
        if not self._draining:
            self._lifecycle(now)

        # 2b) dead fleet — no replica can ever serve again (none healthy
        # or recoverable, no spawn hook armed): fail the stranded work
        # now instead of parking it forever
        recoverable = any(r.state in (HEALTHY, SUSPECT)
                          for r in self.replicas)
        can_spawn = (self.spawn_fn is not None
                     and self.policy.spawn_depth is not None)
        if not recoverable and not can_spawn and not self._draining:
            stranded = self.queue.evict_all() + [r for _, r in self._parked]
            self._parked = []
            for req in stranded:
                reg.counter("serve.fleet.retries_exhausted").inc()
                delivered.append(self._finish_unplaced(
                    req, "error", "no_replicas", now))

        # 3) placement — parked retries first (oldest work), then front
        if not self._draining:
            still = []
            for eligible_at, req in self._parked:
                if eligible_at > now or not self._try_place(req, now):
                    still.append((eligible_at, req))
            self._parked = still
            while self.queue.depth and self._placeable():
                req = self.queue.pop()
                if not self._try_place(req, now):
                    # the pop is not a lease on delivery: placement can
                    # race a transport death (place RPC hits a socket
                    # that just died → drop → False) and the request
                    # must survive it — park for the next sweep
                    self._parked.append((now, req))

        # 3b) proactive hot-prefix replication — before the poll so a
        # prefix shipped this tick is visible to next tick's placement
        if self.policy.kv_hot_refs is not None and not self._draining:
            self._replicate_hot_prefixes()

        # 4) poll the replicas, deliver-or-retry what they finish
        for rep in self.replicas:
            if rep.state == RETIRED:
                continue
            try:
                finished = rep.transport.poll()
            except TransportError:
                self._transport_drop(rep, now)
                continue
            for resp in finished:
                req = self._tracked.get(resp.request_id)
                if (resp.status == "error"
                        and resp.finish_reason in RETRYABLE_REASONS
                        and req is not None):
                    rep.had_error_this_tick = True
                    self._placed_on.pop(req.id, None)
                    delivered.extend(self.reclaim([req], now))
                    continue
                out = self._deliver(resp)
                if out is not None:
                    delivered.append(out)

        # 5) fleet gauges
        counts = self.counts()
        for state, n in counts.items():
            reg.gauge(f"serve.fleet.replicas_{state}").set(n)
        reg.gauge("serve.fleet.front_depth").set(self.queue.depth)
        reg.gauge("serve.fleet.parked").set(len(self._parked))
        if self.journal is not None:
            reg.gauge("serve.fleet.journal_records").set(
                self.journal.records_written)
            reg.gauge("serve.fleet.journal_bytes").set(
                self.journal.bytes_written)
            age = self.journal.fsync_age_s
            if age is not None:
                reg.gauge("serve.fleet.journal_fsync_age_s").set(age)
        for rep in self.replicas:
            tr = rep.transport
            reg.gauge(labelled("serve.fleet.replica.state",
                               replica=rep.index)).set(
                _STATE_CODE[rep.state])
            if rep.state == RETIRED:
                continue
            try:
                h = tr.health()
                reg.gauge(labelled("serve.fleet.replica.queue_depth",
                                   replica=rep.index)).set(tr.queue_depth)
                reg.gauge(labelled("serve.fleet.replica.live_slots",
                                   replica=rep.index)).set(tr.live_slots)
                reg.gauge(labelled("serve.fleet.rpc_inflight",
                                   replica=rep.index)).set(tr.rpc_inflight)
                reg.gauge(labelled("serve.fleet.rpc_retries",
                                   replica=rep.index)).set(tr.rpc_retries)
                reg.gauge(labelled("serve.fleet.heartbeat_age_s",
                                   replica=rep.index)).set(
                    h.heartbeat_age_s)
            except TransportError:
                self._transport_drop(rep, now)
        # responses salvaged off a dropped transport this tick (already
        # in the ledger) — surface them through the normal return path
        if self._pending_out:
            delivered.extend(self._pending_out)
            self._pending_out = []
        self._tick_index = tick_idx + 1
        return delivered

    # -- convenience loops -------------------------------------------------

    def run_until_idle(self, max_ticks: int = 1_000_000) -> List[Response]:
        """Tick until every tracked request delivered. With every
        replica dead this still terminates: retries exhaust their
        budgets and the dead-fleet sweep fails anything stranded."""
        delivered: List[Response] = []
        for _ in range(max_ticks):
            if self.idle:
                return delivered
            delivered.extend(self.tick())
        raise RuntimeError(
            f"fleet not idle after {max_ticks} ticks (front="
            f"{self.queue.depth}, parked={len(self._parked)}, "
            f"replicas={self.counts()})")
