"""Process replicas: each fleet replica a real OS process on a wire.

The other half of the transport split (:mod:`.control` is the
transport-agnostic control plane): :class:`ProcessReplicaTransport`
spawns ``python -m pipe_tpu.fleet.proc`` as a fresh interpreter that
owns its OWN engine, jit cache and KV pool — the process boundary is
the isolation the in-process fleet can't give (a wedged replica's GIL,
a poisoned XLA client, a leaked device buffer die with their process).

Wire protocol
-------------
Length-prefixed frames over one loopback TCP connection per replica
(the child connects back to the parent's listener, so the parent never
needs to guess a child port, and reconnect is child-initiated):

* frame = 4-byte big-endian length + 4-byte CRC32 + 4-byte sequence
  number + payload (the length covers crc+seq+payload). The CRC is
  over seq+payload: a corrupt frame raises :class:`FrameCorrupt` and
  is treated as a broken CONNECTION — drop, re-dial, replay — never a
  half-parsed RPC. Sequence numbers are per-direction monotonic
  (``seq=0`` marks unsequenced control frames: hello/spec/ready/
  shutdown and the lossy heartbeat stream); a receiver suppresses
  ``seq <= last_seen``, so frames replayed after a reconnect — the
  parent's pending-RPC replay, the child's retained-response replay —
  and chaos-duplicated frames are deduplicated instead of
  double-delivered;
* payload = msgpack (JSON + base64 fallback when msgpack is absent)
  of one message dict; numpy arrays ride an explicit
  ``{"__nd__": dtype, shape, data}`` envelope, so KV-handoff payloads
  (int8 codes + f32 scales) cross the wire without pickling;
* messages: parent→child **ops** (``place``/``cancel``/``evict``/
  ``drain``/``export_prefix``/``import_prefix``/``invalidate_prefix``/
  ``cached_prefix``/``shutdown``), each carrying an ``rpc`` id the
  child echoes in its ``reply`` (value or ``error=[type, msg]``, so
  ``QueueFull``/``EngineDraining``/``ValueError`` re-raise with their
  in-process semantics); child→parent **responses** (terminal
  :class:`~..serve.queue.Response` records, streamed as they finish)
  and **heartbeats** (the health signals the controller's state
  machine runs on — ``slow_streak``, ``miss_ewma``, ``stuck_slots``,
  ``consecutive_decode_errors`` — plus depth/live/idle/drained, every
  ``heartbeat_interval_s`` whether or not anything else moved).

Clock domains: the parent and child clocks are unrelated, so deadlines
NEVER cross the wire absolute — ``place`` ships ``remaining_s`` (time
left) and ``age_s`` (time since submit) and the child re-anchors both
on its own monotonic clock. Reconnect: a dropped connection is retried
by the child against the same listener for ``reconnect_timeout_s``;
the parent re-sends still-pending RPC frames on the new connection
(counted in ``rpc_retries``). Past the window the transport reports
dead and every call raises :class:`~.control.TransportError` — the
controller then reclaims the in-flight requests from its OWN ledger
(the authoritative map; a late response for a reclaimed id is dropped
here, never delivered twice).

Per-RPC deadlines: inside the total ``rpc_timeout_s`` window, ``_rpc``
re-sends its frame on an exponential-backoff schedule
(``rpc_retry_base_s`` doubling up to ``rpc_retry_max_s``, jittered
deterministically from the rpc id) — a frame lost to a delay spike or
a partition recovers without waiting out the whole window, and the
re-sent frame carries the SAME sequence number, so the child either
suppresses it or re-serves the cached reply.

Adversarial wire chaos: pass a :class:`~..resilience.chaos.ChaosPlan`
with ``wire_partition``/``wire_delay``/``wire_corrupt``/``wire_dup``
faults (indexed by OUTGOING parent frame count, replica-addressed via
``Fault.stage``) and the transport injects them at the framing layer —
see :func:`apply_wire_chaos`.

Controller restart: ``rejoin={"port", "token", "pid", ...}`` (from
:meth:`ProcessReplicaTransport.rejoin_info`, journaled at spawn)
re-binds the SAME listener port with the SAME token and adopts the
*running* child instead of spawning — the child's ordinary reconnect
loop re-dials the reborn listener and replays its retained response
frames. Responses for ids the new parent has not adopted yet are
buffered (``adopt``/``seal_rejoin``) so the journal's recovery pass
can salvage work that finished while no controller was alive.

The child ticks ITSELF — the async-tick contract. The controller's
``poll()`` just drains what the reader thread buffered.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.telemetry import MetricsRegistry, get_registry
from ..serve.queue import Request, Response
from .control import ReplicaHealth, ReplicaTransport, TransportError

try:
    import msgpack
    HAVE_MSGPACK = True
except Exception:                                 # pragma: no cover
    msgpack = None
    HAVE_MSGPACK = False

__all__ = ["ProcessReplicaTransport", "ReplicaSpec", "FleetSpawnError",
           "FrameCorrupt", "apply_wire_chaos", "check_spawn_capability"]


class FleetSpawnError(RuntimeError):
    """The platform cannot launch JAX child processes — raised BEFORE
    any replica process is attempted, with the remedy in the message."""


@dataclasses.dataclass
class ReplicaSpec:
    """Everything a child process needs to build its replica engine —
    plain data only (it crosses the wire as the handshake frame). The
    child constructs ``PipelinedLM(LMConfig(**lm_cfg), n_stages)``,
    initializes params from ``init_seed`` (replica homogeneity: every
    replica derives the same weights from the same seed — shipping
    params through the frame protocol is pointless when init is
    deterministic), and wraps a
    :class:`~..serve.engine.SingleDeviceSlotBackend` +
    :class:`~..serve.engine.ServeEngine`."""

    lm_cfg: Dict[str, Any]
    n_stages: int = 1
    init_seed: int = 0
    num_slots: int = 2
    max_len: int = 96
    gen: Dict[str, Any] = dataclasses.field(default_factory=dict)
    buckets: Optional[List[int]] = None
    decode_chunk: int = 1
    # Disaggregated serving (fleet/disagg.py): the replica's phase
    # role. "mixed" (default) serves whole requests — the PR 13
    # behavior, byte-identical. "prefill" runs only the chunked-prefill
    # program (requests arrive clamped to max_new_tokens=1 and retire
    # at the first token); "decode" runs only the resident decode loop
    # over prefixes seated by import_prefix — a decode-only engine
    # refuses prompts with no cached prefix instead of re-prefilling.
    role: str = "mixed"
    kv_block_size: Optional[int] = None
    kv_pool_blocks: Optional[int] = None
    kv_dtype: Optional[str] = None
    # KV gen-2: spill cold blocks to the child's host RAM under
    # pressure, and (when kv_hot_refs is set) advertise the prefix
    # directory + hot digests on heartbeat frames so the controller can
    # place by prefix and replicate hot nodes proactively
    kv_offload: bool = False
    kv_offload_blocks: Optional[int] = None
    kv_hot_refs: Optional[int] = None
    prefill_chunk: int = 16
    queue_capacity: int = 256
    watchdog: bool = True
    heartbeat_interval_s: float = 0.1
    jax_platform: str = "cpu"
    local_devices: int = 1
    # fleet observability: when True the child snapshots its registry
    # (mergeable deltas) and drains its trace-event buffer onto ``obs``
    # frames piggybacked on the heartbeat cadence; when False the child
    # runs a null registry + null event log and ships NOTHING — the
    # zero-overhead pledge, asserted by the frame census test.
    # ``obs_max_bytes`` bounds one obs frame; oversized telemetry is
    # dropped (never blocks or backs up the data plane).
    telemetry: bool = True
    obs_max_bytes: int = 65536


# ---------------------------------------------------------------------------
# spawn capability (satellite: runtime/_multiproc_check discipline)


def _spawn_env(repo_root: Optional[str] = None,
               jax_platform: str = "cpu") -> Dict[str, str]:
    """Child environment, the ``runtime/_multiproc_check`` discipline:
    fresh interpreters must not boot an accelerator plugin meant for
    the parent (it would hang platform selection) and must not inherit
    a forced device count — the child picks its own platform."""
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = jax_platform
    return env


def check_spawn_capability(executable: Optional[str] = None, *,
                           probe: bool = False) -> None:
    """Refuse a process-transport fleet up front, with a clear error,
    when this platform cannot fork/spawn JAX child processes — the
    failure mode ``runtime/_multiproc_check`` documents (sandboxes
    without subprocess, stripped interpreters, no loopback sockets).
    ``probe=True`` additionally launches a trivial child interpreter
    (slower; the transport does it implicitly anyway on first spawn).
    Raises :class:`FleetSpawnError`; returns None when spawning looks
    possible."""
    exe = executable if executable is not None else sys.executable
    remedy = ("process-transport replicas are fresh interpreters "
              "(python -m pipe_tpu.fleet.proc); run on a platform where "
              "subprocesses and loopback sockets are available, or use "
              "the in-process fleet (--fleet inproc / --fleet thread)")
    if not exe or not os.path.exists(exe):
        raise FleetSpawnError(
            f"cannot spawn JAX child processes: python executable "
            f"{exe!r} does not exist — {remedy}")
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.bind(("127.0.0.1", 0))
        finally:
            s.close()
    except OSError as e:
        raise FleetSpawnError(
            f"cannot spawn JAX child processes: loopback sockets are "
            f"unavailable ({e}) — {remedy}")
    if probe:
        try:
            r = subprocess.run([exe, "-c", "import sys; sys.exit(0)"],
                               env=_spawn_env(), timeout=60,
                               stdout=subprocess.DEVNULL,
                               stderr=subprocess.DEVNULL)
        except (OSError, subprocess.SubprocessError) as e:
            raise FleetSpawnError(
                f"cannot spawn JAX child processes: probe launch failed "
                f"({type(e).__name__}: {e}) — {remedy}")
        if r.returncode != 0:
            raise FleetSpawnError(
                f"cannot spawn JAX child processes: probe interpreter "
                f"exited {r.returncode} — {remedy}")


# ---------------------------------------------------------------------------
# frame codec


def _nd_encode(obj):
    if isinstance(obj, np.ndarray):
        return {"__nd__": str(obj.dtype), "shape": list(obj.shape),
                "data": obj.tobytes()}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"cannot encode {type(obj).__name__} on the wire")


def _nd_decode(d):
    if "__nd__" in d:
        data = d["data"]
        if isinstance(data, str):                 # JSON fallback: base64
            data = base64.b64decode(data)
        return np.frombuffer(data, dtype=np.dtype(d["__nd__"])).reshape(
            d["shape"]).copy()
    return d


def _pack(msg: dict) -> bytes:
    if HAVE_MSGPACK:
        return msgpack.packb(msg, default=_nd_encode, use_bin_type=True)

    def jsonable(o):                              # pragma: no cover
        if isinstance(o, np.ndarray):
            return {"__nd__": str(o.dtype), "shape": list(o.shape),
                    "data": base64.b64encode(o.tobytes()).decode()}
        if isinstance(o, bytes):
            return {"__b64__": base64.b64encode(o).decode()}
        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        raise TypeError(type(o).__name__)
    return json.dumps(msg, default=jsonable).encode()


def _unpack(buf: bytes) -> dict:
    if HAVE_MSGPACK:
        return msgpack.unpackb(buf, raw=False, object_hook=_nd_decode,
                               strict_map_key=False)

    def hook(d):                                  # pragma: no cover
        if "__b64__" in d:
            return base64.b64decode(d["__b64__"])
        return _nd_decode(d)
    return json.loads(buf.decode(), object_hook=hook)


class FrameCorrupt(OSError):
    """A frame failed its CRC32. Raised by :func:`recv_frame` and
    treated by both wire ends as a broken CONNECTION — the stream is
    severed and replayed on a fresh dial, so a corrupt frame can never
    surface as a half-parsed RPC or a mangled response."""


def _frame(buf: bytes, seq: int = 0) -> bytes:
    """Wrap one packed payload: length | crc32(seq+payload) | seq |
    payload, with the length prefix covering crc+seq+payload."""
    seq_bytes = struct.pack(">I", seq)
    crc = zlib.crc32(seq_bytes + buf) & 0xFFFFFFFF
    return struct.pack(">II", 8 + len(buf), crc) + seq_bytes + buf


def send_frame(sock: socket.socket, msg: dict,
               lock: Optional[threading.Lock] = None, *,
               seq: int = 0) -> bytes:
    frame = _frame(_pack(msg), seq)
    if lock is not None:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)
    return frame


def recv_frame(sock: socket.socket) -> Optional[dict]:
    """One frame, or None on clean EOF. Raises OSError on a broken
    connection mid-frame and :class:`FrameCorrupt` (an OSError) on a
    checksum mismatch. A nonzero sequence number is surfaced to the
    dispatcher as ``msg["_seq"]`` for duplicate suppression."""
    head = _recv_exact(sock, 4)
    if head is None:
        return None
    (n,) = struct.unpack(">I", head)
    body = _recv_exact(sock, n)
    if body is None:
        raise OSError("connection closed mid-frame")
    if n < 8:
        raise FrameCorrupt(f"frame too short for crc+seq header ({n}B)")
    (crc,) = struct.unpack(">I", body[:4])
    if zlib.crc32(body[4:]) & 0xFFFFFFFF != crc:
        raise FrameCorrupt("frame checksum mismatch")
    (seq,) = struct.unpack(">I", body[4:8])
    msg = _unpack(body[8:])
    if seq and isinstance(msg, dict):
        msg["_seq"] = seq
    return msg


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    chunks = []
    got = 0
    while got < n:
        c = sock.recv(n - got)
        if not c:
            return None
        chunks.append(c)
        got += len(c)
    return b"".join(chunks)


# ---------------------------------------------------------------------------
# adversarial wire chaos (the framing-layer injection point)


def apply_wire_chaos(plan, index: int, frame: bytes,
                     replica: int = 0) -> Tuple[List[bytes], float]:
    """Transform one OUTGOING frame per the plan's ``wire_*`` faults
    covering frame ``index`` to ``replica`` (``Fault.stage``). Returns
    ``(frames, partition_s)``:

    * ``wire_delay``   — sleep ``magnitude`` seconds first (capped 5s);
    * ``wire_corrupt`` — flip the frame's last byte AFTER the checksum
      was computed, so the receiver's CRC rejects it;
    * ``wire_dup``     — the frame twice (the receiver's sequence
      suppression must collapse them);
    * ``wire_partition`` — ``([], magnitude)``: the frame is lost with
      the connection and the caller severs the wire for ``magnitude``
      seconds (capped 30s) before accepting the re-dial.

    With no covering fault (or no plan) the frame passes untouched —
    the zero-overhead pledge at this layer is one attribute check.
    """
    if plan is None or not plan:
        return [frame], 0.0
    wire_fault = getattr(plan, "wire_fault", None)
    if wire_fault is None:
        return [frame], 0.0
    f = wire_fault("wire_partition", index, replica)
    if f is not None:
        return [], min(max(float(f.magnitude), 0.0), 30.0)
    f = wire_fault("wire_delay", index, replica)
    if f is not None:
        time.sleep(min(max(float(f.magnitude), 0.0), 5.0))
    frames = [frame]
    if wire_fault("wire_corrupt", index, replica) is not None:
        frames = [frame[:-1] + bytes([frame[-1] ^ 0xFF])]
    if wire_fault("wire_dup", index, replica) is not None:
        frames = frames * 2
    return frames, 0.0


# ---------------------------------------------------------------------------
# parent side: the transport


_ERRORS = {"QueueFull": None, "EngineDraining": None, "ValueError":
           ValueError, "PoolExhausted": None, "RuntimeError": RuntimeError}


def _raise_remote(name: str, msg: str):
    from ..serve.engine import EngineDraining
    from ..serve.kvpool import PoolExhausted
    from ..serve.queue import QueueFull
    cls = {"QueueFull": QueueFull, "EngineDraining": EngineDraining,
           "ValueError": ValueError, "PoolExhausted": PoolExhausted,
           }.get(name, RuntimeError)
    raise cls(msg)


class _ExternalChild:
    """Popen-shaped handle over a child THIS parent did not spawn — the
    controller-restart rejoin adopts a running replica process by pid.
    ``poll``/``wait``/``kill`` go through ``os.kill`` (signal 0 probes
    liveness); with no pid recorded the child is assumed alive and only
    the wire can prove otherwise."""

    def __init__(self, pid: Optional[int]):
        self.pid = pid
        self.returncode: Optional[int] = None
        self.stderr = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if self.pid is None:
            return None
        try:
            os.kill(self.pid, 0)
        except (ProcessLookupError, PermissionError):
            self.returncode = -1
            return self.returncode
        return None

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("replica-child",
                                                timeout or 0)
            time.sleep(0.02)
        return self.returncode

    def kill(self) -> None:
        if self.pid is None:
            return
        import signal
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


class ProcessReplicaTransport(ReplicaTransport):
    """One replica behind a real OS process. Spawn-time cost is a full
    interpreter + jit warmup per replica — this transport is for fleets
    that run, not for unit-test churn (tests mark it slow)."""

    def __init__(self, spec: ReplicaSpec, *,
                 clock=None,
                 connect_timeout_s: float = 120.0,
                 rpc_timeout_s: float = 120.0,
                 reconnect_timeout_s: float = 5.0,
                 rpc_retry_base_s: float = 2.0,
                 rpc_retry_max_s: float = 30.0,
                 rpc_retry_jitter: float = 0.25,
                 executable: Optional[str] = None,
                 bind_host: Optional[str] = None,
                 advertise_host: Optional[str] = None,
                 chaos=None, chaos_replica: int = 0,
                 rejoin: Optional[dict] = None):
        if rejoin is None:
            check_spawn_capability(executable)
        self.spec = spec
        self.role = spec.role
        self.clock = clock or time.monotonic
        self._rpc_timeout_s = rpc_timeout_s
        self._reconnect_timeout_s = reconnect_timeout_s
        self._rpc_retry_base_s = rpc_retry_base_s
        self._rpc_retry_max_s = rpc_retry_max_s
        self._rpc_retry_jitter = rpc_retry_jitter
        self.rpc_inflight = 0
        self.rpc_retries = 0
        self.handoff_bytes = 0
        # wire hardening state: per-direction sequence counters, the
        # chaos injection plan, and the counters the drills gate on
        self.chaos = chaos
        self.chaos_replica = int(chaos_replica)
        self._wire_index = 0          # outgoing frame index (chaos key)
        self._partition_until = 0.0   # accept-hold horizon (wire_partition)
        # parent->child seqs fold a random per-incarnation epoch into
        # the header's high 12 bits: a restarted controller's frames
        # land under a FRESH epoch, so the child resets its dedup
        # window and reply cache instead of mistaking the new parent's
        # rpc ids for the dead parent's (stale cached replies)
        self._epoch = (int.from_bytes(os.urandom(2), "big") % 4095) + 1
        self._send_seq = 0            # parent->child sequence counter
        self._recv_seq_max = 0        # newest child response seq seen
        self.wire_crc_rejects = 0     # parent-side CRC rejections
        self.wire_dup_suppressed = 0  # frames dropped by seq dedup
        self.wire_resends = 0         # per-RPC backoff re-sends
        # controller-restart rejoin: while the window is open, response
        # frames for unknown ids are BUFFERED (they may be orphans the
        # journal recovery will adopt or salvage) instead of dropped
        self._adopt_window = rejoin is not None
        self._orphan_buf: Dict[int, dict] = {}
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: Dict[int, list] = {}       # rpc id -> [event, reply]
        self._pending_frames: Dict[int, bytes] = {}
        self._rpc_next = 0
        self._inflight: Dict[int, Request] = {}
        self._responses: "deque[Response]" = deque()
        self._hb: Dict[str, Any] = {}
        self._hb_at: Optional[float] = None
        self._dead: Optional[str] = None
        self._draining = False
        self._closed = False
        # shipped-telemetry state (the parent half of the obs plane):
        # merged registry of everything this child ever shipped, age of
        # the newest obs frame, child-reported drop count, and the
        # bounded child trace-event stream the observer stitches
        self.obs_tokens_out = 0
        self.obs_responses_out = 0
        self._obs_registry = MetricsRegistry()
        self._obs_at: Optional[float] = None
        self._obs_seq = -1
        self._obs_dropped = 0
        self._obs_events: "deque[dict]" = deque(maxlen=50_000)
        self._frame_census: Dict[str, int] = {}

        # The wire binds a real host/port: bind_host is the interface
        # the parent listens on (default loopback — byte-identical to
        # the PR 13 wire), advertise_host the address the child dials
        # back to (defaults to bind_host, or loopback for the wildcard
        # "0.0.0.0"/"::" binds, which are not dialable addresses). The
        # reconnect/replay and heartbeat machinery is address-agnostic:
        # the child re-dials whatever it was told.
        self._bind_host = bind_host or "127.0.0.1"
        if advertise_host is None:
            advertise_host = ("127.0.0.1"
                              if self._bind_host in ("0.0.0.0", "::")
                              else self._bind_host)
        self._advertise_host = advertise_host
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        bind_port = 0 if rejoin is None else int(rejoin["port"])
        try:
            self._listener.bind((self._bind_host, bind_port))
        except OSError as e:
            self._listener.close()
            raise FleetSpawnError(
                f"cannot bind the fleet wire on {self._bind_host!r}"
                f":{bind_port}: {e}")
        self._listener.listen(1)
        port = self._listener.getsockname()[1]
        if rejoin is not None:
            # controller restart: the child is already RUNNING and
            # re-dialing the port its dead parent listened on — rebind
            # it with the recorded token, adopt the process by pid, and
            # learn the engine caps over the wire instead of the
            # spec/ready handshake (the engine was built long ago)
            self._token = str(rejoin["token"])
            self._proc = _ExternalChild(rejoin.get("pid"))
            self._sock = self._accept(connect_timeout_s)
            self._reader = threading.Thread(target=self._read_loop,
                                            name="fleet-proc-reader",
                                            daemon=True)
            self._reader.start()
            st = self._rpc({"op": "status"}, timeout=connect_timeout_s)
            self.default_max_new_tokens_ = int(st["default_max_new_tokens"])
            self.queue_capacity_ = int(st["queue_capacity"])
            self.num_slots = int(st["num_slots"])
            return
        self._token = base64.b64encode(os.urandom(12)).decode()
        exe = executable if executable is not None else sys.executable
        self._proc = subprocess.Popen(
            [exe, "-m", "pipe_tpu.fleet.proc",
             "--port", str(port), "--token", self._token,
             "--host", self._advertise_host],
            env=_spawn_env(jax_platform=spec.jax_platform),
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        try:
            self._sock = self._accept(connect_timeout_s)
            send_frame(self._sock,
                       {"op": "spec", "spec": dataclasses.asdict(spec)},
                       self._send_lock)
            ready = recv_frame(self._sock)
            if not ready or ready.get("op") != "ready":
                err = b""
                self._kill_child()
                if self._proc.stderr is not None:
                    err = self._proc.stderr.read() or b""
                raise TransportError(
                    f"replica child never became ready: {ready!r}; child "
                    f"stderr: {err.decode(errors='replace')[-2000:]}")
            self.default_max_new_tokens_ = int(
                ready["default_max_new_tokens"])
            self.queue_capacity_ = int(ready["queue_capacity"])
            self.num_slots = int(ready["num_slots"])
        except Exception:
            self._kill_child()
            raise
        self._reader = threading.Thread(target=self._read_loop,
                                        name="fleet-proc-reader",
                                        daemon=True)
        self._reader.start()

    def rejoin_info(self) -> dict:
        """Everything a future parent needs to re-register this child
        WITHOUT spawning (journaled at fleet construction): the
        listener port to rebind, the hello token, the child pid, and
        the spec to rebuild the transport around."""
        return {"port": self._listener.getsockname()[1],
                "token": self._token, "pid": self._proc.pid,
                "host": self._bind_host, "role": self.role,
                "spec": dataclasses.asdict(self.spec)}

    # -- controller-restart reconciliation ---------------------------------

    def remote_request_ids(self) -> List[int]:
        """Ask the child which request ids it currently holds (queued
        or decoding) — the reconciliation query a rejoined controller
        runs against the journal's placed-but-unanswered set."""
        st = self._rpc({"op": "status"}) or {}
        return sorted({int(i) for i in (st.get("queued") or [])} |
                      {int(i) for i in (st.get("live") or [])})

    def orphan_response_ids(self) -> List[int]:
        """Ids whose response frames arrived during the adopt window
        before any controller claimed them — already finished remotely,
        salvageable without re-running."""
        with self._state_lock:
            return sorted(self._orphan_buf)

    def adopt(self, req: Request) -> bool:
        """Adopt one orphaned request during rejoin. If its response
        is already buffered, move it onto the normal poll path (True:
        the id will deliver without re-placement); otherwise register
        it in-flight so the child's (re)shipped response frame is
        accepted instead of discarded."""
        with self._state_lock:
            msg = self._orphan_buf.pop(req.id, None)
            if msg is not None:
                self._responses.append(self._response_from(msg))
                self.obs_tokens_out += len(msg["tokens"])
                self.obs_responses_out += 1
                return True
            self._inflight[req.id] = req
            return False

    def seal_rejoin(self) -> List[Response]:
        """Close the adopt window: unknown response ids go back to
        being discarded (the exactly-once drop path). Returns any
        still-unclaimed buffered responses — journaled-terminal dups
        the controller must NOT deliver twice, or never-submitted ids
        from a torn journal tail the caller may surface."""
        out: List[Response] = []
        with self._state_lock:
            self._adopt_window = False
            for rid in sorted(self._orphan_buf):
                out.append(self._response_from(self._orphan_buf[rid]))
            self._orphan_buf.clear()
        return out

    @property
    def crc_rejects_total(self) -> int:
        """Corrupt frames rejected on BOTH ends of this wire (parent
        reader + the child's count, shipped via heartbeat)."""
        with self._state_lock:
            child = int(self._hb.get("crc_rejects", 0))
        return self.wire_crc_rejects + child

    # -- connection management -------------------------------------------

    def _accept(self, timeout_s: float) -> socket.socket:
        # accept in short slices so a child that DIED (crash, SIGKILL)
        # surfaces in ~a quarter second instead of silently eating the
        # whole connect window — a place() RPC blocked behind this is
        # inside the controller's tick loop
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                if self._proc.poll() is not None:
                    err = b""
                    if self._proc.stderr is not None:
                        err = self._proc.stderr.read() or b""
                    raise TransportError(
                        f"replica child exited rc={self._proc.returncode} "
                        f"before connecting: "
                        f"{err.decode(errors='replace')[-2000:]}")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportError(
                        f"replica child did not connect within "
                        f"{timeout_s}s")
                held = self._partition_until - time.monotonic()
                if held > 0:
                    # chaos partition: refuse the re-dial for the hold.
                    # The child's connect attempts queue in the kernel
                    # listen backlog and land the instant the hold
                    # lifts, so the heal is a plain accept
                    time.sleep(min(held, 0.25, max(remaining, 0.01)))
                    continue
                try:
                    self._listener.settimeout(min(0.25, remaining))
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError as e:
                    # listener torn down by close() while we waited
                    raise TransportError(f"listener closed: {e}")
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                try:
                    hello = recv_frame(conn)
                except OSError:        # corrupt/truncated hello: not ours
                    conn.close()
                    continue
                if hello and hello.get("op") == "hello" \
                        and hello.get("token") == self._token:
                    return conn
                conn.close()                      # wrong token: not ours
        finally:
            try:
                self._listener.settimeout(None)
            except OSError:
                pass

    def _chaos_send_locked(self, frame: bytes) -> None:
        """Send one parent->child frame through the chaos plan's wire
        faults. MUST be called holding ``_send_lock``. A partition
        fault drops the frame, severs the live connection and arms
        ``_partition_until`` so ``_accept`` refuses the re-dial for the
        hold; the pending-frame replay re-sends the lost RPC when the
        wire heals."""
        index = self._wire_index
        self._wire_index += 1
        frames, partition_s = apply_wire_chaos(
            self.chaos, index, frame, self.chaos_replica)
        if partition_s > 0:
            self._partition_until = time.monotonic() + partition_s
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise OSError("chaos wire partition")
        for f in frames:
            self._sock.sendall(f)

    def _read_loop(self) -> None:
        while not self._closed:
            try:
                msg = recv_frame(self._sock)
                if msg is None:
                    raise OSError("EOF")
            except FrameCorrupt as e:
                # a corrupt frame poisons the stream boundary: the only
                # safe resync is a fresh connection. Count it, sever,
                # and fall into the reconnect+replay path — the RPC it
                # carried (either direction) is replayed, never
                # half-parsed
                if self._closed:
                    return
                self.wire_crc_rejects += 1
                get_registry().counter(
                    "serve.fleet.wire_crc_rejects").inc()
                try:
                    self._sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                if not self._reconnect():
                    if not self._closed:
                        self._mark_dead(
                            f"corrupt frame ({e}) and reconnect "
                            f"window expired")
                    return
                continue
            except OSError as e:
                if self._closed:
                    return
                if not self._reconnect():
                    if not self._closed:
                        self._mark_dead(
                            f"connection lost ({e}) and reconnect "
                            f"window expired")
                    return
                continue
            self._dispatch(msg)

    def _reconnect(self) -> bool:
        """Wait for the child to re-dial the listener; re-send pending
        RPC frames on the fresh connection (counted as retries)."""
        if self._proc.poll() is not None:
            return False
        try:
            conn = self._accept(self._reconnect_timeout_s)
        except TransportError:
            return False
        with self._send_lock:
            old, self._sock = self._sock, conn
            try:
                old.close()
            except OSError:
                pass
            with self._state_lock:
                frames = list(self._pending_frames.values())
            for frame in frames:
                try:
                    self._chaos_send_locked(frame)
                    self.rpc_retries += 1
                except OSError:
                    # the fresh wire died mid-replay (or a chaos
                    # partition severed it): report success anyway so
                    # the read loop's next recv failure routes back
                    # through _reconnect, whose _accept honors the
                    # partition hold — only an expired window or a
                    # dead child ends the recovery
                    break
        return True

    @staticmethod
    def _response_from(msg: dict) -> Response:
        return Response(
            request_id=msg["id"], tokens=list(msg["tokens"]),
            status=msg["status"], finish_reason=msg["finish_reason"],
            prompt_len=msg["prompt_len"],
            ttft=msg.get("ttft"), latency=msg.get("latency"))

    def _dispatch(self, msg: dict) -> None:
        op = msg.get("op")
        seq = int(msg.pop("_seq", 0))
        self._frame_census[op] = self._frame_census.get(op, 0) + 1
        if op == "reply":
            with self._state_lock:
                ent = self._pending.get(msg.get("rpc"))
            if ent is not None:
                ent[1] = msg
                ent[0].set()
        elif op == "response":
            # only response frames carry a child->parent wire sequence;
            # a chaos wire_dup (or the post-reconnect retained-frame
            # replay) presents already-taken seqs, suppressed here so
            # delivery stays exactly-once
            if seq:
                with self._state_lock:
                    if seq <= self._recv_seq_max:
                        self.wire_dup_suppressed += 1
                        return
                    self._recv_seq_max = seq
            rid = msg["id"]
            with self._state_lock:
                known = rid in self._inflight
                if known:
                    self._inflight.pop(rid, None)
                    self._responses.append(self._response_from(msg))
                    # delivery-synchronized per-replica accounting: the
                    # tokens rode THIS frame, so the count can never
                    # outrun (or trail) what the parent actually took —
                    # the reconciliation invariant the observer sums
                    self.obs_tokens_out += len(msg["tokens"])
                    self.obs_responses_out += 1
                elif self._adopt_window:
                    # controller-restart rejoin: ids the dead parent
                    # placed are unknown to THIS parent until the
                    # journal reconciliation adopts them — buffer
                    # instead of discarding
                    self._orphan_buf[rid] = dict(msg)
            # unknown id: the controller reclaimed it over a drop — the
            # stale record is discarded HERE so delivery stays exactly-once
        elif op == "hb":
            with self._state_lock:
                self._hb = msg
                self._hb_at = time.monotonic()
        elif op == "obs":
            events = msg.get("events") or []
            with self._state_lock:
                new_seq = int(msg.get("seq", self._obs_seq + 1))
                if new_seq <= self._obs_seq:
                    # replayed/duplicated obs frame (chaos wire_dup or
                    # reconnect): already merged, drop it
                    self.wire_dup_suppressed += 1
                    return
                self._obs_registry.merge_snapshot(msg.get("metrics") or {})
                self._obs_events.extend(events)
                self._obs_at = time.monotonic()
                self._obs_seq = new_seq
                new_dropped = int(msg.get("dropped", 0))
                just_dropped = max(new_dropped - self._obs_dropped, 0)
                self._obs_dropped = new_dropped
            reg = get_registry()
            reg.counter("serve.fleet.obs_frames").inc()
            reg.counter("serve.fleet.obs_bytes").inc(
                int(msg.get("nbytes", 0)))
            reg.counter("serve.fleet.obs_events").inc(len(events))
            if just_dropped:
                reg.counter("serve.fleet.obs_dropped").inc(just_dropped)

    def _mark_dead(self, reason: str) -> None:
        self._dead = reason
        with self._state_lock:
            pend = list(self._pending.values())
        for ent in pend:
            ent[0].set()

    def _check(self) -> None:
        if self._dead is not None:
            raise TransportError(f"replica transport dead: {self._dead}")
        if self._proc.poll() is not None and self._proc.returncode != 0:
            self._mark_dead(
                f"replica process exited rc={self._proc.returncode}")
            raise TransportError(f"replica transport dead: {self._dead}")

    # -- rpc ---------------------------------------------------------------

    def _rpc(self, msg: dict, timeout: Optional[float] = None):
        self._check()
        ev = threading.Event()
        with self._state_lock:
            rid = self._rpc_next
            self._rpc_next += 1
            self._pending[rid] = [ev, None]
        msg = dict(msg, rpc=rid)
        total_s = timeout if timeout is not None else self._rpc_timeout_s
        deadline = time.monotonic() + total_s
        # deterministic per-rpc jitter (Knuth hash of the rpc id):
        # concurrent retries against a struggling child spread out
        # instead of stampeding in lockstep
        jitter = 1.0 + self._rpc_retry_jitter * (
            (rid * 2654435761 & 0xFFFF) / 65535.0)
        with self._send_lock:
            # the frame is BUILT once, under the send lock, so its wire
            # sequence is allocated in send order and every re-send
            # (retry or reconnect replay) repeats the same seq — the
            # child's dedup window recognizes it
            self._send_seq = (self._send_seq + 1) & 0xFFFFF
            if self._send_seq == 0:
                # 20-bit counter wrapped: roll the epoch so the child's
                # window resets rather than treating a million frames
                # as duplicates
                self._epoch = (self._epoch % 4095) + 1
                self._send_seq = 1
            frame = _frame(_pack(msg), (self._epoch << 20) | self._send_seq)
            with self._state_lock:
                # register BEFORE sending: if the send races a
                # connection drop, the reconnect replay finds the frame
                # and re-sends it — marking the transport dead here
                # would preempt a recovery the read loop was about to
                # complete
                self._pending_frames[rid] = frame
            try:
                self._chaos_send_locked(frame)
            except OSError:
                pass        # reconnect replay (or _mark_dead) resolves it
        try:
            self.rpc_inflight += 1
            attempt = 0
            while True:
                wait_s = min(self._rpc_retry_base_s * (2.0 ** attempt),
                             self._rpc_retry_max_s) * jitter
                wait_s = min(wait_s, max(deadline - time.monotonic(), 0.0))
                if ev.wait(wait_s):
                    break
                if time.monotonic() >= deadline:
                    self._mark_dead(
                        f"rpc {msg.get('op')} timed out after "
                        f"{total_s}s ({attempt + 1} attempts)")
                    raise TransportError(
                        f"replica transport dead: {self._dead}")
                # attempt deadline passed without a reply: re-send the
                # SAME frame (same rpc id, same wire seq) and back off
                # exponentially — a dup the child already answered is
                # answered again from its reply cache
                attempt += 1
                self.wire_resends += 1
                try:
                    with self._send_lock:
                        self._chaos_send_locked(frame)
                except OSError:
                    pass    # reconnect replay carries it instead
            with self._state_lock:
                reply = self._pending[rid][1]
            if reply is None:                     # woken by _mark_dead
                raise TransportError(
                    f"replica transport dead: {self._dead}")
            if "error" in reply:
                _raise_remote(reply["error"][0], reply["error"][1])
            return reply.get("value")
        finally:
            with self._state_lock:
                self._pending.pop(rid, None)
                self._pending_frames.pop(rid, None)
            self.rpc_inflight = max(self.rpc_inflight - 1, 0)

    # -- ReplicaTransport ---------------------------------------------------

    def place(self, req: Request) -> None:
        now = self.clock()
        remaining = (req.deadline - now if req.deadline is not None
                     else None)
        payload = {"op": "place", "id": req.id,
                   "prompt": list(map(int, req.prompt)),
                   "max_new_tokens": req.max_new_tokens,
                   "seed": req.seed, "priority": req.priority,
                   "attempts": req.attempts,
                   "remaining_s": remaining,
                   "age_s": max(now - req.submitted_at, 0.0),
                   "cancelled": bool(req.cancelled),
                   "trace": req.trace_id}
        self._rpc(payload)                        # raises remote errors
        req.attempts += 1                         # placement ledger
        with self._state_lock:
            self._inflight[req.id] = req

    def poll(self) -> List[Response]:
        self._check()
        out: List[Response] = []
        with self._state_lock:
            while self._responses:
                out.append(self._responses.popleft())
        return out

    def salvage(self) -> List[Response]:
        """Drain the parent-side response buffer WITHOUT the liveness
        check. These responses were accepted off live frames (and their
        tokens counted into ``obs_tokens_out``) before the wire died;
        the controller's drop path delivers them instead of re-running
        their requests, keeping the delivered-token reconciliation
        exact across a SIGKILL."""
        out: List[Response] = []
        with self._state_lock:
            while self._responses:
                out.append(self._responses.popleft())
        return out

    def evict_queued(self) -> List[int]:
        return [int(i) for i in (self._rpc({"op": "evict"}) or [])]

    def cancel(self, request_id: int) -> bool:
        return bool(self._rpc({"op": "cancel", "id": request_id}))

    def drain(self) -> None:
        self._draining = True
        self._rpc({"op": "drain"})

    @property
    def drained(self) -> bool:
        with self._state_lock:
            quiet = not self._inflight and not self._responses
        return self._draining and quiet and bool(self._hb.get("drained"))

    @property
    def idle(self) -> bool:
        with self._state_lock:
            return not self._inflight and not self._responses

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._dead is None and self._proc.poll() is None:
                send_frame(self._sock, {"op": "shutdown"}, self._send_lock)
                self._proc.wait(timeout=5)
        except (OSError, subprocess.TimeoutExpired):
            pass
        self._kill_child()
        for s in (self._sock, self._listener):
            try:
                s.close()
            except OSError:
                pass

    def _kill_child(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:     # pragma: no cover
                pass

    # -- placement surface --------------------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._state_lock:
            live = int(self._hb.get("live", 0))
            return max(len(self._inflight) - live, 0)

    @property
    def queue_capacity(self) -> int:
        return self.queue_capacity_

    @property
    def live_slots(self) -> int:
        return int(self._hb.get("live", 0))

    def validate(self, prompt_len: int, max_new_tokens: int) -> None:
        # mirror of the child's admission checks, evaluated lazily: the
        # child re-validates at place() and ships the ValueError back
        if max_new_tokens > self.default_max_new_tokens_:
            raise ValueError(
                f"max_new_tokens {max_new_tokens} exceeds the engine cap "
                f"({self.default_max_new_tokens_})")
        if prompt_len + max_new_tokens > self.spec.max_len:
            raise ValueError(
                f"prompt_len {prompt_len} + max_new_tokens "
                f"{max_new_tokens} exceeds the slot cache "
                f"({self.spec.max_len} rows)")

    @property
    def default_max_new_tokens(self) -> int:
        return self.default_max_new_tokens_

    # -- shipped telemetry ---------------------------------------------------

    def obs_view(self):
        """The parent-side view of everything this child shipped:
        ``(registry, age_s, seq, events)`` — the merged
        :class:`~..obs.telemetry.MetricsRegistry`, seconds since the
        newest obs frame (None before the first), the child's frame
        sequence number, and a copy of the bounded trace-event stream.
        """
        with self._state_lock:
            age = (time.monotonic() - self._obs_at
                   if self._obs_at is not None else None)
            return (self._obs_registry, age, self._obs_seq,
                    list(self._obs_events))

    # -- health -------------------------------------------------------------

    def health(self) -> ReplicaHealth:
        alive = self._dead is None and self._proc.poll() is None
        age = (time.monotonic() - self._hb_at
               if self._hb_at is not None else float("inf"))
        hb = self._hb
        return ReplicaHealth(
            slow_streak=int(hb.get("slow_streak", 0)),
            miss_ewma=float(hb.get("miss_ewma", 0.0)),
            stuck_slots=int(hb.get("stuck_slots", 0)),
            consecutive_decode_errors=int(hb.get("decode_errors", 0)),
            heartbeat_age_s=age if self._hb_at is not None else 0.0,
            alive=alive)

    # -- KV handoff ---------------------------------------------------------

    def export_prefix(self, prompt: Sequence[int]) -> Optional[dict]:
        payload = self._rpc({"op": "export_prefix",
                             "prompt": list(map(int, prompt))})
        return payload or None

    def import_prefix(self, payload: dict) -> int:
        n = int(self._rpc({"op": "import_prefix", "payload": payload}) or 0)
        if n:
            self.handoff_bytes += int(payload.get("nbytes", 0))
        return n

    def invalidate_prefix(self, prompt: Sequence[int]) -> int:
        return int(self._rpc({"op": "invalidate_prefix",
                              "prompt": list(map(int, prompt))}) or 0)

    def cached_prefix_blocks(self, prompt: Sequence[int]) -> int:
        return int(self._rpc({"op": "cached_prefix",
                              "prompt": list(map(int, prompt))}) or 0)

    def prefix_directory(self) -> Optional[dict]:
        # Read from the last heartbeat, never an RPC: placement runs
        # every tick and must not add a round trip per candidate. The
        # directory is at most one heartbeat stale — acceptable for a
        # placement heuristic (a stale hit just degrades to cold).
        kv = self._hb.get("kv")
        return kv.get("directory") if kv else None

    def hot_prefixes(self, min_refs: int) -> List[dict]:
        kv = self._hb.get("kv")
        return list(kv.get("hot", ())) if kv else []

    # -- test hook ----------------------------------------------------------

    def drop_connection(self) -> None:
        """Sever the current socket WITHOUT touching the child — the
        transport-drop drill. The child's reconnect loop re-dials the
        listener; pending RPCs re-send on the fresh connection."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# child side: the replica worker


def _build_engine(spec: ReplicaSpec, event_log=None):
    """Construct the replica's model/backend/engine from the handshake
    spec — imports deferred so the parent-side transport never pays
    for jax."""
    if spec.jax_platform == "cpu" and spec.local_devices > 1:
        from ..utils.platform import force_cpu_platform
        force_cpu_platform(spec.local_devices)
    import jax

    from ..inference import GenerationConfig
    from ..models.transformer_lm import LMConfig, PipelinedLM
    from ..resilience import TickWatchdog
    from ..serve.buckets import BucketSpec
    from ..serve.engine import ServeEngine, SingleDeviceSlotBackend
    from ..serve.queue import RequestQueue

    model = PipelinedLM(LMConfig(**spec.lm_cfg), spec.n_stages)
    params = model.init(jax.random.key(spec.init_seed))
    gen = GenerationConfig(**spec.gen)
    buckets = (BucketSpec.of(*spec.buckets)
               if spec.buckets is not None else None)
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=spec.num_slots, max_len=spec.max_len,
        gen=gen, buckets=buckets, decode_chunk=spec.decode_chunk,
        kv_block_size=spec.kv_block_size,
        kv_pool_blocks=spec.kv_pool_blocks, kv_dtype=spec.kv_dtype,
        kv_offload=spec.kv_offload,
        kv_offload_blocks=spec.kv_offload_blocks,
        prefill_chunk=spec.prefill_chunk)
    wd = TickWatchdog() if spec.watchdog else None
    return ServeEngine(backend,
                       RequestQueue(capacity=spec.queue_capacity),
                       watchdog=wd, event_log=event_log,
                       phase=spec.role)


def _child_op(engine, msg: dict, now: float):
    """Apply one parent op; returns the reply value (exceptions
    propagate to the op loop, which ships them back by name)."""
    op = msg["op"]
    if op == "place":
        req = Request(
            id=int(msg["id"]), prompt=list(msg["prompt"]),
            max_new_tokens=int(msg["max_new_tokens"]),
            seed=int(msg["seed"]), priority=int(msg["priority"]),
            deadline=(now + msg["remaining_s"]
                      if msg.get("remaining_s") is not None else None),
            submitted_at=now - float(msg.get("age_s", 0.0)),
            cancelled=bool(msg.get("cancelled", False)),
            # engine.place() increments: the wire ships the
            # pre-placement count so both ledgers agree after
            attempts=int(msg["attempts"]),
            trace_id=msg.get("trace"))
        engine.place(req)
        return True
    if op == "cancel":
        return engine.cancel(int(msg["id"]))
    if op == "evict":
        return [r.id for r in engine.evict_queued()]
    if op == "drain":
        engine.drain()
        return True
    if op == "status":
        # the controller-restart reconciliation query: engine caps (the
        # rejoin handshake's replacement for the spec/ready exchange)
        # plus every request id this replica still holds
        return {"default_max_new_tokens": engine.backend.gen.max_new_tokens,
                "queue_capacity": engine.queue.capacity,
                "num_slots": engine.backend.num_slots,
                "queued": [r.id for r in engine.queue.admission_order()],
                "live": [s.req.id for s in engine._slots if s is not None]}
    backend = engine.backend
    pool = getattr(backend, "pool", None)
    if op == "export_prefix":
        exp = getattr(backend, "export_prefix_payload", None)
        return exp(msg["prompt"], codec="int8") if exp is not None else None
    if op == "import_prefix":
        imp = getattr(backend, "import_prefix_payload", None)
        return imp(msg["payload"]) if imp is not None else 0
    if op == "invalidate_prefix":
        if pool is None:
            return 0
        return pool.invalidate(pool.prefix_hashes(msg["prompt"]))
    if op == "cached_prefix":
        if pool is None:
            return 0
        return pool.cached_prefix_blocks(msg["prompt"])
    raise ValueError(f"unknown fleet op {op!r}")


def _heartbeat(engine, kv_hot_refs: Optional[int] = None,
               crc_rejects: int = 0) -> dict:
    wd = engine.watchdog
    hb = {"op": "hb",
          "slow_streak": wd.slow_streak if wd is not None else 0,
          "miss_ewma": wd.miss_ewma if wd is not None else 0.0,
          "stuck_slots": wd.stuck_slots if wd is not None else 0,
          "decode_errors": engine.consecutive_decode_errors,
          "depth": engine.queue.depth, "live": engine.live_slots,
          "idle": engine.idle, "draining": engine.draining,
          "drained": engine.drained}
    if crc_rejects:
        # only when a corrupt frame was actually seen: a clean wire
        # ships exactly the former heartbeat bytes
        hb["crc_rejects"] = int(crc_rejects)
    # KV gen-2 directory: piggybacked on the heartbeat cadence (one
    # beat stale at the controller, which is fine — placement is a
    # heuristic, correctness never depends on the directory). Only when
    # kv_hot_refs is armed: an unarmed fleet ships exactly the PR 13
    # heartbeat bytes.
    if kv_hot_refs is not None:
        pool = getattr(engine.backend, "pool", None)
        if pool is not None:
            hb["kv"] = {
                "directory": pool.prefix_digest_summary(),
                "hot": pool.hot_prefixes(kv_hot_refs),
            }
    return hb


def worker(port: int, token: str, host: str = "127.0.0.1") -> None:
    """The replica process: connect back to the parent, build the
    engine from the spec frame, then self-tick — serve ops between
    ticks, stream terminal responses, heartbeat on an interval, and
    re-dial the listener if the connection drops. ``host`` is the
    parent's advertised address (loopback by default; a real interface
    address for cross-host fleets)."""
    import selectors

    def dial() -> socket.socket:
        s = socket.create_connection((host, port), timeout=30)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_frame(s, {"op": "hello", "token": token})
        return s

    sock = dial()
    spec_msg = recv_frame(sock)
    assert spec_msg and spec_msg.get("op") == "spec", spec_msg
    spec = ReplicaSpec(**spec_msg["spec"])
    if spec.telemetry:
        from ..obs.fleet_obs import TraceBuffer
        trace_buf = TraceBuffer()
    else:
        # zero-overhead pledge: a disabled registry hands the jitted
        # bodies the shared null instruments (HLO byte-identical) and
        # the wire carries no obs frames at all
        from ..obs.telemetry import null_registry, set_registry
        set_registry(null_registry())
        trace_buf = None
    engine = _build_engine(spec, event_log=trace_buf)
    send_frame(sock, {"op": "ready",
                      "default_max_new_tokens":
                          engine.backend.gen.max_new_tokens,
                      "queue_capacity": engine.queue.capacity,
                      "num_slots": engine.backend.num_slots})

    sel = selectors.DefaultSelector()
    sel.register(sock, selectors.EVENT_READ)
    send_lock = threading.Lock()
    link = {"sock": sock, "up": True}
    # wire-hardening state: responses carry a child->parent sequence
    # (the parent suppresses replays), recent response frames are
    # retained for post-reconnect replay, and replies to already-seen
    # rpc ids are answered from cache instead of re-executing the op
    wire = {"resp_seq": 0, "recv_max": 0, "epoch": 0, "crc_rejects": 0}
    reply_cache: OrderedDict = OrderedDict()
    sent_responses = deque(maxlen=256)

    def resync(old: socket.socket) -> Optional[socket.socket]:
        """Reconnect loop: re-dial the parent's listener until it
        answers or the window closes, then replay every retained
        response frame — the parent's sequence dedup swallows the ones
        it already took, so a response lost to a partition or a corrupt
        frame is delivered exactly once."""
        sel.unregister(old)
        try:
            old.close()
        except OSError:
            pass
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                s = dial()
            except OSError:
                time.sleep(0.1)
                continue
            sel.register(s, selectors.EVENT_READ)
            with send_lock:
                link["sock"] = s
                try:
                    for frame in list(sent_responses):
                        s.sendall(frame)
                except OSError:
                    pass     # the next recv failure re-enters resync
            return s
        return None

    def ship_response(resp) -> None:
        """Frame one terminal response with the next wire sequence and
        retain it for replay. The frame is appended to the retained
        window BEFORE the send, so a send that dies mid-frame still
        replays after resync."""
        msg = {"op": "response", "id": resp.request_id,
               "tokens": list(map(int, resp.tokens)),
               "status": resp.status,
               "finish_reason": resp.finish_reason,
               "prompt_len": resp.prompt_len,
               "ttft": resp.ttft, "latency": resp.latency}
        with send_lock:
            wire["resp_seq"] += 1
            frame = _frame(_pack(msg), wire["resp_seq"])
            sent_responses.append(frame)
            link["sock"].sendall(frame)

    obs_state = {"seq": 0, "base": {}, "dropped": 0}
    obs_lock = threading.Lock()

    def ship_obs() -> None:
        # Telemetry piggybacks on the heartbeat cadence: a mergeable
        # registry delta plus the drained trace-event buffer, bounded
        # by spec.obs_max_bytes. Oversized payloads shed their events
        # first (metrics are tiny and keep counters continuous), then
        # drop outright — telemetry is strictly lossy-over-blocking and
        # can never stall the data plane.
        with obs_lock:
            reg = get_registry()
            metrics = reg.snapshot(mergeable=True, base=obs_state["base"])
            events = trace_buf.drain() if trace_buf is not None else []
            if not metrics and not events:
                return
            obs_state["seq"] += 1
            msg = {"op": "obs", "seq": obs_state["seq"], "metrics": metrics,
                   "events": events, "dropped": obs_state["dropped"]}
            buf = _pack(msg)
            if len(buf) > spec.obs_max_bytes and events:
                obs_state["dropped"] += len(events)
                msg["events"] = []
                msg["dropped"] = obs_state["dropped"]
                buf = _pack(msg)
            if len(buf) > spec.obs_max_bytes:
                obs_state["dropped"] += 1
                return
            msg["nbytes"] = len(buf)
        send_frame(link["sock"], msg, send_lock)

    def hb_pump() -> None:
        # Heartbeats come from their OWN thread: the main loop blocks
        # for seconds inside jit compiles (first prefill/decode of each
        # bucket), and a parent watching heartbeat age would declare a
        # perfectly healthy-but-compiling replica wedged. XLA releases
        # the GIL while compiling, so this thread keeps the health
        # signal flowing through exactly those stalls. Send failures
        # are ignored — the main loop owns reconnect.
        while link["up"]:
            time.sleep(spec.heartbeat_interval_s)
            try:
                # heartbeats are UNSEQUENCED (seq 0): they interleave
                # with response frames on the wire, and advancing the
                # parent's response-seq window from here would let a
                # beat sent during a drop suppress a replayed response
                send_frame(link["sock"],
                           _heartbeat(engine, spec.kv_hot_refs,
                                      wire["crc_rejects"]),
                           send_lock)
                if spec.telemetry:
                    ship_obs()
            except OSError:
                pass

    threading.Thread(target=hb_pump, daemon=True).start()

    while True:
        now = time.monotonic()
        busy = not engine.idle or (engine.draining and not engine.drained)
        events = sel.select(timeout=0.0 if busy else 0.02)
        for _ in events:
            try:
                msg = recv_frame(sock)
                if msg is None:
                    raise OSError("EOF")
            except FrameCorrupt:
                # a frame that fails its checksum poisons the stream
                # boundary — never parse past it. Count it (shipped on
                # the next heartbeat) and resync on a fresh connection;
                # the parent re-sends whatever the bad frame carried
                wire["crc_rejects"] += 1
                sock = resync(sock)
                if sock is None:
                    return
                continue
            except OSError:
                sock = resync(sock)
                if sock is None:
                    return
                continue
            seq = int(msg.pop("_seq", 0))
            if msg.get("op") == "shutdown":
                try:
                    if spec.telemetry:
                        ship_obs()    # final deltas before the lights go out
                    send_frame(sock, {"op": "reply",
                                      "rpc": msg.get("rpc"),
                                      "value": True}, send_lock)
                except OSError:
                    pass
                return
            if seq:
                # parent seqs = (epoch << 20) | counter. A fresh epoch
                # is a NEW parent incarnation (controller restart):
                # reset the dedup window and reply cache so the new
                # parent's rpc ids are never mistaken for the dead
                # parent's
                ep, ctr = seq >> 20, seq & 0xFFFFF
                if ep != wire["epoch"]:
                    wire["epoch"] = ep
                    wire["recv_max"] = 0
                    reply_cache.clear()
                if ctr <= wire["recv_max"]:
                    # replayed or duplicated op frame (chaos wire_dup,
                    # an rpc-timeout re-send, or the reconnect replay).
                    # If the op already ran, re-ship its cached reply
                    # rather than running it twice; an unseen rpc under
                    # an old seq (post-corruption realignment) falls
                    # through and runs normally — the parent's
                    # reply/response dedup is the backstop
                    cached = reply_cache.get(msg.get("rpc"))
                    if cached is not None:
                        try:
                            send_frame(sock, cached, send_lock)
                        except OSError:
                            sock = resync(sock)
                            if sock is None:
                                return
                        continue
                else:
                    wire["recv_max"] = ctr
            try:
                value = _child_op(engine, msg, time.monotonic())
                reply = {"op": "reply", "rpc": msg.get("rpc"),
                         "value": value}
            except Exception as e:                # noqa: BLE001 — wire it
                reply = {"op": "reply", "rpc": msg.get("rpc"),
                         "error": [type(e).__name__, str(e)]}
            if msg.get("rpc") is not None:
                reply_cache[msg["rpc"]] = reply
                while len(reply_cache) > 512:
                    reply_cache.popitem(last=False)
            try:
                send_frame(sock, reply, send_lock)
            except OSError:
                sock = resync(sock)
                if sock is None:
                    return

        if busy:
            for resp in engine.tick():
                try:
                    ship_response(resp)
                except OSError:
                    sock = resync(sock)
                    if sock is None:
                        return



def _main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="pipe_tpu fleet replica worker (spawned by "
                    "ProcessReplicaTransport; not a user entry point)")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--token", required=True)
    ap.add_argument("--host", default="127.0.0.1",
                    help="parent listener address to dial back to")
    args = ap.parse_args(argv)
    worker(args.port, args.token, args.host)
    return 0


if __name__ == "__main__":
    sys.exit(_main())
