"""Core: microbatch, partition, schedule, remat."""
