"""Shared memory-plan arithmetic: slot counts → estimated bytes.

One formula, two consumers. The scheduled executor's ``memory_plan``
(``parallel/scheduled.py``) reports its static per-device buffer counts,
and the auto-planner (``core/planner.py``) must PREDICT peak memory for
candidate configs it has not built yet — if each derived the slot
arithmetic independently the two would drift, and the planner's memory
cap would gate on a formula the executor no longer implements. So the
checkpoint-mode → slot-count mapping lives here:

* ``stash``: the schedule's live stashed-input window, per virtual stage
  (``Schedule.stash_slots``), times the interleave depth ``v``;
* ``residual``: stored-backward residuals — all ``v * stash`` under
  ``checkpoint='never'``, one per virtual stage under ``'except_last'``
  (only the in-flight micro-batch's), none under ``'always'``;
* ``policy residual``: the remat-policy-saved subset parked by RECOMPUTE
  micro-batches (same FIFO lifetime as the stash), present only when a
  policy is installed under a recompute mode;
* ``wstash``: deferred-W cotangent parks of split-backward tables —
  live only under ``checkpoint='never'`` (recompute modes run the fused
  backward at B and the W slots park nothing);
* ``taps``: the structural-split tap store (``split_stage``), one slot
  per stash window per virtual stage;
* ``grad park``: overlapped transport's one-cycle cotangent park.

:func:`estimate_memory` then prices the slots: activation-sized windows
at ``act_bytes``, residual windows at ``residual_bytes``, plus the
static ``param_bytes`` replicated across weights, grads, and optimizer
moments. It is an ESTIMATE — XLA fusion slack and transport double
buffers are not modeled — but it is monotone in the knobs the planner
searches (m, schedule, v, checkpoint), which is what a pruning cap
needs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

__all__ = ["MemoryPlanInputs", "activation_slot_plan", "estimate_memory"]

_CHECKPOINT_MODES = ("always", "except_last", "never")


@dataclasses.dataclass(frozen=True)
class MemoryPlanInputs:
    """Per-virtual-stage slot counts plus the mode switches that gate
    them. ``stash_slots``/``wstash_slots`` are the schedule's RAW
    per-virtual-stage windows (``Schedule.stash_slots``/``wstash_slots``
    or their comm-shifted widenings) — the checkpoint gating happens in
    :func:`activation_slot_plan`, not in the caller."""

    v: int
    stash_slots: int
    wstash_slots: int = 0
    checkpoint: str = "except_last"
    has_remat_policy: bool = False
    split_stage: bool = False
    overlap: bool = False
    grad_park_slots: int = 0    # per virtual stage, overlapped transport

    def __post_init__(self):
        if self.checkpoint not in _CHECKPOINT_MODES:
            raise ValueError(
                f"checkpoint must be one of {_CHECKPOINT_MODES}, "
                f"got {self.checkpoint!r}")
        if self.v < 1:
            raise ValueError(f"v must be >= 1, got {self.v}")


def activation_slot_plan(inputs: MemoryPlanInputs) -> dict:
    """The executor's static per-device buffer counts for one config —
    the exact dict ``ScheduledPipeline.memory_plan`` reports (minus the
    executor-only ``cycles``/``transport``/phase/skip keys)."""
    v, Sg = inputs.v, inputs.stash_slots
    Wg = inputs.wstash_slots if inputs.checkpoint == "never" else 0
    R = {"always": 0, "except_last": v, "never": v * Sg}[inputs.checkpoint]
    # Policy-shaped residual slots (dynamic path): recompute micro-batches
    # park their policy-saved subset here, one FIFO slot per (virtual
    # stage, stash window) — same lifetime as the stash.
    Rp = (v * Sg if inputs.has_remat_policy
          and inputs.checkpoint != "never" else 0)
    plan = {"stash_slots": v * Sg,
            "stash_slots_per_virtual_stage": Sg,
            "residual_slots": R,
            "policy_residual_slots": Rp,
            "h_last_slots": Sg,
            "wstash_slots": v * Wg,
            "taps_slots": v * Sg if inputs.split_stage else 0,
            "virtual_stages_per_device": v}
    if inputs.overlap:
        plan["grad_park_slots"] = v * inputs.grad_park_slots
    return plan


def estimate_memory(plan_inputs: Union[MemoryPlanInputs, dict], *,
                    act_bytes: int,
                    residual_bytes: Optional[int] = None,
                    param_bytes: int = 0,
                    opt_moments: int = 2) -> int:
    """Estimated peak per-device bytes of one pipeline config.

    ``plan_inputs`` is either :class:`MemoryPlanInputs` or an
    already-computed slot-plan dict (``activation_slot_plan`` /
    ``ScheduledPipeline.memory_plan`` output — both spell the keys the
    same way, by construction). ``act_bytes`` prices one micro-batch
    boundary activation; ``residual_bytes`` one stored-backward residual
    tree (defaults to ``act_bytes`` — exact for matmul-chain stages whose
    residual is dominated by the stashed input); ``param_bytes`` the
    device's weight shard, counted once for weights, once for grads, and
    ``opt_moments`` more times for the optimizer (2 = Adam)."""
    plan = (activation_slot_plan(plan_inputs)
            if isinstance(plan_inputs, MemoryPlanInputs) else plan_inputs)
    if residual_bytes is None:
        residual_bytes = act_bytes
    act_slots = (plan["stash_slots"] + plan["h_last_slots"]
                 + plan["wstash_slots"] + plan["taps_slots"]
                 + plan.get("grad_park_slots", 0))
    res_slots = plan["residual_slots"] + plan["policy_residual_slots"]
    return int(act_slots * act_bytes + res_slots * residual_bytes
               + (2 + opt_moments) * param_bytes)
