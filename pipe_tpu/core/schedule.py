"""Pipeline schedules as data.

The reference's only schedule is the GPipe fill–drain clock-cycle wavefront
(``_clock_cycles``, reference ``pipeline.py:63-79``): at cycle ``k`` every pair
``(i, j)`` with ``i + j == k`` runs, for micro-batch ``i`` of ``m`` on stage ``j``
of ``n``, giving ``m + n - 1`` cycles and a bubble fraction of
``(n - 1) / (m + n - 1)``.

Here a schedule is a first-class object producing that wavefront as *data*, so
the same (i, j) contract drives both the serial emulator and the compiled SPMD
executor, and so alternative schedules (1F1B, interleaved 1F1B — BASELINE.json
configs #4) slot in without touching the executors.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Tuple

__all__ = [
    "clock_cycles",
    "bubble_fraction",
    "Schedule",
    "GPipeSchedule",
    "OneFOneBSchedule",
    "InterleavedSchedule",
    "get_schedule",
]


def clock_cycles(m: int, n: int) -> Iterator[List[Tuple[int, int]]]:
    """Anti-diagonal wavefront: cycle k runs {(i, j) : i + j == k}.

    Direct capability match of reference ``pipeline.py:63-79``.
    m micro-batches over n stages in m + n - 1 cycles.
    """
    for k in range(m + n - 1):
        yield [(k - j, j) for j in range(max(0, k - m + 1), min(n, k + 1))]


def bubble_fraction(m: int, n: int) -> float:
    """GPipe analytical bubble: (n-1)/(m+n-1) of cycles are idle fill/drain."""
    return (n - 1) / (m + n - 1)


@dataclasses.dataclass(frozen=True)
class Schedule:
    """Base schedule: maps (micro-batches m, stages n) to an ordered cycle list.

    ``cycles(m, n)[k]`` is the list of (microbatch, stage) pairs that may run
    concurrently at cycle k. Executors rely only on this contract.
    """

    name: str = "base"

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        raise NotImplementedError

    def num_cycles(self, m: int, n: int) -> int:
        return len(self.cycles(m, n))

    def bubble(self, m: int, n: int) -> float:
        total = self.num_cycles(m, n) * n
        busy = m * n
        return (total - busy) / total


@dataclasses.dataclass(frozen=True)
class GPipeSchedule(Schedule):
    """Synchronous fill–drain (the reference's schedule, ``pipeline.py:63-79``)."""

    name: str = "gpipe"

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        return [list(c) for c in clock_cycles(m, n)]


@dataclasses.dataclass(frozen=True)
class OneFOneBSchedule(Schedule):
    """1F1B forward ordering.

    Forward cycles are identical to GPipe's wavefront (the forward pass of 1F1B
    is the same fill); the memory win comes from interleaving backward
    micro-batches, which in this framework is realized by the remat policy and
    the compiled backward of the SPMD executor rather than a runtime queue.
    Kept as a distinct schedule so the executor can cap in-flight activations at
    ``n`` instead of ``m``.
    """

    name: str = "1f1b"

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        return [list(c) for c in clock_cycles(m, n)]

    def max_live_microbatches(self, m: int, n: int) -> int:
        return min(m, n)


@dataclasses.dataclass(frozen=True)
class InterleavedSchedule(Schedule):
    """Interleaved 1F1B: each device hosts ``v`` non-contiguous stage chunks.

    With v virtual stages per device the fill bubble shrinks by ~v
    (BASELINE.json config #4: 8-stage BERT-large, interleaved).

    Contract note: ``cycles(m, n)`` takes ``n`` = the TOTAL number of stages
    the executor holds (already virtual), same as every other schedule — the
    interleaving changes *placement* (``device_of``: virtual stage s lives on
    device ``s % n_devices``) and the per-device bubble model, not the
    wavefront over stages.
    """

    name: str = "interleaved"
    v: int = 2

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        return [list(c) for c in clock_cycles(m, n)]

    def virtual_stages(self, n_devices: int) -> int:
        return n_devices * self.v

    def device_of(self, virtual_stage: int, n_devices: int) -> int:
        return virtual_stage % n_devices

    def device_bubble(self, m: int, n_devices: int) -> float:
        """Per-device fill/drain bubble ≈ (d-1)/(m·v + d-1): v× smaller fill."""
        d = n_devices
        return (d - 1) / (m * self.v + d - 1)


_SCHEDULES = {
    "gpipe": GPipeSchedule,
    "1f1b": OneFOneBSchedule,
    "interleaved": InterleavedSchedule,
}


def get_schedule(name: str, **kwargs) -> Schedule:
    if name not in _SCHEDULES:
        raise ValueError(f"unknown schedule {name!r}; options: {sorted(_SCHEDULES)}")
    return _SCHEDULES[name](**kwargs)
