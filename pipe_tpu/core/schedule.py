"""Pipeline schedules as data.

The reference's only schedule is the GPipe fill–drain clock-cycle wavefront
(``_clock_cycles``, reference ``pipeline.py:63-79``): at cycle ``k`` every pair
``(i, j)`` with ``i + j == k`` runs, for micro-batch ``i`` of ``m`` on stage ``j``
of ``n``, giving ``m + n - 1`` cycles and a bubble fraction of
``(n - 1) / (m + n - 1)``.

Here a schedule is a first-class object producing that wavefront as *data*, so
the same (i, j) contract drives both the serial emulator and the compiled SPMD
executor, and so alternative schedules (1F1B, interleaved 1F1B — BASELINE.json
configs #4) slot in without touching the executors.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Iterator, List, Optional, Tuple

import numpy as np

__all__ = [
    "clock_cycles",
    "bubble_fraction",
    "Schedule",
    "GPipeSchedule",
    "OneFOneBSchedule",
    "InterleavedSchedule",
    "InterleavedOneFOneBSchedule",
    "get_schedule",
    "verify_op_tables",
    "verify_interleaved_op_tables",
    "IDLE",
    "FWD",
    "BWD",
    "WGRAD",
    "ZeroBubbleSchedule",
    "ZeroBubbleDeepSchedule",
    "verify_zb_op_tables",
    "zb_joint_capacity",
    "shift_comm_tables",
    "verify_shifted_op_tables",
    "overlap_fifo_capacity",
    "align_phase_tables",
    "segment_phases",
    "compile_phases",
    "PhaseSegment",
    "PhaseProgram",
    "PhaseVerdict",
    "PHASE_KLASS_FB",
    "PHASE_KLASS_FBW",
    "ElasticPlan",
    "replan_stage_loss",
]

# Op codes for the (cycle, stage) tables driving the manual fwd+bwd executor
# (parallel.scheduled.ScheduledPipeline). BWD is the combined backward
# (input AND weight grads in one slot) for the classic schedules; zero-bubble
# tables split it into BWD (= B, input-grad only, rides the rigid reverse
# ring) and WGRAD (= W, weight-grad only, freely deferrable).
IDLE, FWD, BWD, WGRAD = 0, 1, 2, 3


def _place(op: np.ndarray, mbi: np.ndarray, t: int, j: int,
           code: int, i: int) -> None:
    if op[t, j] != IDLE:
        raise AssertionError(
            f"schedule collision at cycle {t}, stage {j}: "
            f"op {op[t, j]} already placed, tried {code} (mb {i})")
    op[t, j] = code
    mbi[t, j] = i


def clock_cycles(m: int, n: int) -> Iterator[List[Tuple[int, int]]]:
    """Anti-diagonal wavefront: cycle k runs {(i, j) : i + j == k}.

    Direct capability match of reference ``pipeline.py:63-79``.
    m micro-batches over n stages in m + n - 1 cycles.
    """
    for k in range(m + n - 1):
        yield [(k - j, j) for j in range(max(0, k - m + 1), min(n, k + 1))]


def bubble_fraction(m: int, n: int) -> float:
    """GPipe analytical bubble: (n-1)/(m+n-1) of cycles are idle fill/drain."""
    return (n - 1) / (m + n - 1)


@dataclasses.dataclass(frozen=True)
class Schedule:
    """Base schedule: maps (micro-batches m, stages n) to an ordered cycle list.

    ``cycles(m, n)[k]`` is the list of (microbatch, stage) pairs that may run
    concurrently at cycle k. Executors rely only on this contract.
    """

    name: str = "base"

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        raise NotImplementedError

    def num_cycles(self, m: int, n: int) -> int:
        return len(self.cycles(m, n))

    def bubble(self, m: int, n: int) -> float:
        total = self.num_cycles(m, n) * n
        busy = m * n
        return (total - busy) / total

    # --- manual fwd+bwd executor contract (parallel.scheduled) ---

    def op_tables(self, m: int, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """``(op[T, n], mb[T, n])`` over ``T = 2(m+n-1)`` uniform slots.

        ``op[t, j]`` says what stage ``j`` does at cycle ``t`` (IDLE/FWD/BWD)
        and ``mb[t, j]`` on which micro-batch. Invariants every table must
        satisfy (asserted by construction + :func:`verify_op_tables`):

        * each (i, j) appears exactly once as FWD and once as BWD;
        * FWD of (i, j) happens strictly after FWD of (i, j-1);
        * BWD of (i, j) happens exactly one cycle after BWD of (i, j+1)
          (gradients ride a reverse ppermute with no buffering);
        * BWD of (i, j) happens after FWD of (i, j).
        """
        raise NotImplementedError

    def stash_slots(self, m: int, n: int) -> int:
        """Max simultaneously-live stashed input activations per stage."""
        raise NotImplementedError

    def wstash_slots(self, m: int, n: int) -> int:
        """Max live deferred-W cotangents per stage (0 unless the schedule
        splits backward into B and W ops — see :class:`ZeroBubbleSchedule`)."""
        return 0

    @property
    def splits_backward(self) -> bool:
        """True when op tables carry separate B (input-grad) and W
        (weight-grad) ops — the zero-bubble lineage. Executors consult this
        to shape carries and to warn on checkpoint modes that defeat the
        split (see :class:`ZeroBubbleSchedule`'s executor note)."""
        return False

    @property
    def v(self) -> int:
        """Interleave depth: virtual stages per device (1 = not interleaved)."""
        return 1


@dataclasses.dataclass(frozen=True)
class GPipeSchedule(Schedule):
    """Synchronous fill–drain (the reference's schedule, ``pipeline.py:63-79``)."""

    name: str = "gpipe"

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        return [list(c) for c in clock_cycles(m, n)]

    def op_tables(self, m: int, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """Fill–drain forward then full reverse wavefront backward.

        Forward is the reference wavefront (FWD of (i, j) at cycle ``i + j``,
        ``pipeline.py:63-79``); backward is its mirror, the order the autograd
        engine discovers at runtime in the reference (LIFO per stage,
        ``pipeline.py:128-132``) — here precomputed as data.
        """
        T = 2 * (m + n - 1)
        op = np.full((T, n), IDLE, np.int32)
        mbi = np.zeros((T, n), np.int32)
        for j in range(n):
            for i in range(m):
                _place(op, mbi, i + j, j, FWD, i)
                _place(op, mbi, (m + n - 1) + (m - 1 - i) + (n - 1 - j),
                       j, BWD, i)
        return op, mbi

    def stash_slots(self, m: int, n: int) -> int:
        """All m forwards complete before any backward: O(m) live inputs."""
        return m


@dataclasses.dataclass(frozen=True)
class OneFOneBSchedule(Schedule):
    """1F1B: one-forward-one-backward with at most ``min(m, n)`` micro-batches
    in flight per stage (the memory property the reference's fork/join
    machinery exists to enable, ``pipeline.py:128-132``; torchgpipe lineage
    ``pipe.py:230-232``).

    Stage ``j`` runs ``min(m, n-j)`` warm-up forwards, then alternates
    backward/forward, then drains backwards:

    * FWD of (i, j) at cycle ``i + j``        for ``i <  n - j`` (warm-up)
    * FWD of (i, j) at cycle ``2i + j``       for ``i >= n - j`` (steady)
    * BWD of (i, j) at cycle ``2n - 1 - j + 2i``

    Same ``2(m+n-1)`` total slots — and hence the same bubble — as GPipe;
    the win is the activation-memory cap, not the bubble.
    """

    name: str = "1f1b"

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        return [list(c) for c in clock_cycles(m, n)]

    def op_tables(self, m: int, n: int) -> Tuple[np.ndarray, np.ndarray]:
        T = 2 * (m + n - 1)
        op = np.full((T, n), IDLE, np.int32)
        mbi = np.zeros((T, n), np.int32)
        for j in range(n):
            for i in range(m):
                tf = i + j if i < n - j else 2 * i + j
                _place(op, mbi, tf, j, FWD, i)
                _place(op, mbi, 2 * n - 1 - j + 2 * i, j, BWD, i)
        return op, mbi

    def stash_slots(self, m: int, n: int) -> int:
        """BWD of i precedes FWD of i + min(m, n) at every stage, so a
        ``min(m, n)``-slot ring buffer of stashed inputs never collides."""
        return min(m, n)


@dataclasses.dataclass(frozen=True)
class InterleavedSchedule(Schedule):
    """Interleaved 1F1B: each device hosts ``v`` non-contiguous stage chunks.

    With v virtual stages per device the fill bubble shrinks by ~v
    (BASELINE.json config #4: 8-stage BERT-large, interleaved).

    Contract note: ``cycles(m, n)`` takes ``n`` = the TOTAL number of stages
    the executor holds (already virtual), same as every other schedule — the
    interleaving changes *placement* (``device_of``: virtual stage s lives on
    device ``s % n_devices``) and the per-device bubble model, not the
    wavefront over stages.
    """

    name: str = "interleaved"
    v: int = 2

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        return [list(c) for c in clock_cycles(m, n)]

    def virtual_stages(self, n_devices: int) -> int:
        return n_devices * self.v

    def device_of(self, virtual_stage: int, n_devices: int) -> int:
        return virtual_stage % n_devices

    def device_bubble(self, m: int, n_devices: int) -> float:
        """Per-device fill/drain bubble ≈ (d-1)/(m·v + d-1): v× smaller fill."""
        d = n_devices
        return (d - 1) / (m * self.v + d - 1)


@dataclasses.dataclass(frozen=True)
class InterleavedOneFOneBSchedule(Schedule):
    """Interleaved 1F1B: ``v`` virtual stages per device, forward AND
    backward as one static table (BASELINE config #4's schedule).

    Virtual stage ``s`` of ``S = v * d`` lives on device ``s % d`` — every
    boundary ``s -> s+1`` is one hop on the WRAPAROUND device ring, so one
    uniform ppermute moves all inter-group traffic. Tables come from a
    greedy constructor honoring the manual executor's transport contract:

    * FWD(i, s) at least one cycle after FWD(i, s-1) (park in stash);
    * BWD(i, s) EXACTLY one cycle after BWD(i, s+1) (cotangents ride the
      reverse ring unbuffered) — backward chains are rigid once seeded, so
      the constructor reserves whole chains at the earliest collision-free
      cycle and fills remaining slots with the deepest available forward;
    * per device one op per cycle.

    vs plain 1F1B of the same S virtual stages, the fill/drain shrinks
    (e.g. m=8, d=4, v=2: 42 cycles vs 46) — the interleave bubble win with
    1F1B's activation cap, where :class:`InterleavedSchedule` (AD executor)
    keeps GPipe's O(m) liveness.
    """

    name: str = "interleaved-1f1b"
    interleave: int = 2

    @property
    def v(self) -> int:
        return self.interleave

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        raise NotImplementedError(
            "interleaved-1f1b is a manual-executor schedule; it has no "
            "forward-only wavefront (use op_tables)")

    @functools.lru_cache(maxsize=64)
    def op_tables(self, m: int, d: int):
        """``(op[T, d], mb[T, d], grp[T, d])`` over devices (not stages).

        Cached: the greedy constructor is pure Python over the whole table
        and is consulted repeatedly (trace time, stash_slots, memory_plan,
        per-log-line bubble reporting); the dataclass is frozen/hashable.
        """
        v = self.interleave
        S = v * d
        max_T = 4 * (m * v + d) + 8
        op = np.full((max_T, d), IDLE, np.int32)
        mbi = np.zeros((max_T, d), np.int32)
        grp = np.zeros((max_T, d), np.int32)
        t_fwd = np.full((m, S), -1)
        t_bwd = np.full((m, S), -1)
        reserved: dict = {}

        def chain_free(t0):
            return all((t0 + (S - 1 - s), s % d) not in reserved
                       and t0 + (S - 1 - s) < max_T for s in range(S))

        def reserve_chain(t0, i):
            for s in range(S):
                reserved[(t0 + (S - 1 - s), s % d)] = (i, s)

        next_seed = 0
        for t in range(max_T):
            while (next_seed < m and 0 <= t_fwd[next_seed, S - 1] < t):
                t0 = t
                while not chain_free(t0):
                    t0 += 1
                reserve_chain(t0, next_seed)
                next_seed += 1
            for p in range(d):
                if (t, p) in reserved:
                    i, s = reserved[(t, p)]
                    op[t, p], mbi[t, p], grp[t, p] = BWD, i, s // d
                    t_bwd[i, s] = t
                    continue
                placed = False
                for g in range(v - 1, -1, -1):      # deepest group first
                    s = g * d + p
                    for i in range(m):
                        if t_fwd[i, s] >= 0:
                            continue
                        if s > 0 and not (0 <= t_fwd[i, s - 1] < t):
                            continue
                        op[t, p], mbi[t, p], grp[t, p] = FWD, i, g
                        t_fwd[i, s] = t
                        placed = True
                        break
                    if placed:
                        break
            if (t_bwd >= 0).all():
                T = t + 1
                return op[:T], mbi[:T], grp[:T]
        raise AssertionError(
            f"interleaved-1f1b table construction did not converge "
            f"(m={m}, d={d}, v={v})")

    def stash_slots(self, m: int, d: int) -> int:
        """Peak live stashed inputs per VIRTUAL stage, from the tables."""
        op, mbi, grp = self.op_tables(m, d)
        _, _, cap = _virtual_times(op, mbi, grp, m, d, self.interleave)
        return cap

    def num_cycles(self, m: int, d: int) -> int:
        return self.op_tables(m, d)[0].shape[0]

    def bubble(self, m: int, d: int) -> float:
        T = self.num_cycles(m, d)
        return (T * d - 2 * m * self.interleave * d) / (T * d)


def _virtual_times(op, mbi, grp, m, d, v):
    """(t_fwd[m, S], t_bwd[m, S], peak stash capacity) from device tables."""
    S = v * d
    T = op.shape[0]
    t_fwd = np.full((m, S), -1)
    t_bwd = np.full((m, S), -1)
    for t in range(T):
        for p in range(d):
            s = grp[t, p] * d + p
            i = mbi[t, p]
            if op[t, p] == FWD:
                assert t_fwd[i, s] == -1, (t, p)
                t_fwd[i, s] = t
            elif op[t, p] == BWD:
                assert t_bwd[i, s] == -1, (t, p)
                t_bwd[i, s] = t
    assert (t_fwd >= 0).all() and (t_bwd >= 0).all(), "missing ops"
    cap = 0
    for s in range(S):
        arrive = t_fwd[:, s] if s == 0 else t_fwd[:, s - 1] + 1
        free = t_bwd[:, s]
        # ring indexing i % cap needs the live set to be a contiguous i
        # range: arrivals and frees must each be monotone in i
        assert (np.diff(arrive) > 0).all(), f"non-FIFO arrivals at {s}"
        assert (np.diff(free) > 0).all(), f"non-FIFO frees at {s}"
        for t in range(T):
            cap = max(cap, int(np.sum((arrive <= t) & (t <= free))))
    return t_fwd, t_bwd, cap


def verify_interleaved_op_tables(op, mbi, grp, m: int, d: int,
                                 v: int) -> None:
    """Invariants for device-major interleaved tables (see
    :class:`InterleavedOneFOneBSchedule`): each (i, virtual stage) runs FWD
    and BWD exactly once on the right device, forward order is strict,
    backward chains step exactly one cycle per hop, and the FIFO property
    the stash ring indexing relies on holds."""
    S = v * d
    t_fwd, t_bwd, _ = _virtual_times(op, mbi, grp, m, d, v)
    for i in range(m):
        for s in range(S):
            assert t_bwd[i, s] > t_fwd[i, s], (i, s)
            if s + 1 < S:
                assert t_fwd[i, s] < t_fwd[i, s + 1], (i, s)
                assert t_bwd[i, s] == t_bwd[i, s + 1] + 1, (i, s)


def verify_op_tables(op: np.ndarray, mbi: np.ndarray, m: int, n: int,
                     stash_slots: Optional[int] = None,
                     comm_shift: int = 1,
                     wstash_slots: Optional[int] = None) -> None:
    """Check the :meth:`Schedule.op_tables` invariants (see docstring there).

    A table passing this check — *including* the stash-capacity check, so
    pass the schedule's ``stash_slots(m, n)`` — executes correctly on the
    manual executor; new schedules only need to emit valid tables.

    ``comm_shift`` selects the transport contract the table is proved
    against. ``1`` (default) is the serialized contract: a boundary value
    sent at cycle ``t`` is consumable at ``t + 1``, and the reverse ring is
    rigid (``BWD(i, j) == BWD(i, j+1) + 1`` exactly). ``>= 2`` is the
    overlapped (software-pipelined) contract of
    :func:`verify_shifted_op_tables`: sends fly while the next cycle
    computes, so every receive must land ``comm_shift`` cycles after its
    send and the reverse ring becomes an elastic receive FIFO.

    W-bearing (split-backward) tables are first-class here: a table with
    any ``WGRAD`` op is routed through the split-aware invariants — W
    strictly after its own B (W consumes B's parked cotangent), and the
    stash-capacity check accounts activations as freed by W, not B (B
    alone does not release the stage input; its W still needs the taps).
    ``wstash_slots`` then additionally bounds the B->W cotangent park.
    """
    if comm_shift > 1:
        verify_shifted_op_tables(
            op, mbi, None, m=m, d=n, v=1, hop=comm_shift,
            stash_slots=stash_slots,
            splits_backward=bool((np.asarray(op) == WGRAD).any()))
        return
    if (np.asarray(op) == WGRAD).any():
        verify_zb_op_tables(op, mbi, m, n, stash_slots=stash_slots,
                            wstash_slots=wstash_slots)
        return
    t_fwd = np.full((m, n), -1)
    t_bwd = np.full((m, n), -1)
    for t in range(op.shape[0]):
        for j in range(n):
            if op[t, j] == FWD:
                assert t_fwd[mbi[t, j], j] == -1, (t, j)
                t_fwd[mbi[t, j], j] = t
            elif op[t, j] == BWD:
                assert t_bwd[mbi[t, j], j] == -1, (t, j)
                t_bwd[mbi[t, j], j] = t
    assert (t_fwd >= 0).all() and (t_bwd >= 0).all(), "missing ops"
    for i in range(m):
        for j in range(n):
            assert t_bwd[i, j] > t_fwd[i, j], f"bwd before fwd at {(i, j)}"
            if j + 1 < n:
                # fwd must be strictly earlier upstream; bwd exactly one
                # cycle later downstream (ring transport, no grad buffering)
                assert t_fwd[i, j] < t_fwd[i, j + 1], (i, j)
                assert t_bwd[i, j] == t_bwd[i, j + 1] + 1, (i, j)
    if stash_slots is not None:
        # Slot i % S parks micro-batch i's input from its arrival (one cycle
        # after the upstream FWD; its own FWD cycle on stage 0) until its BWD
        # reads it — micro-batch i + S must not arrive before that read.
        S = stash_slots
        t_arrive = np.where(
            np.arange(n)[None, :] == 0, t_fwd,
            np.roll(t_fwd, 1, axis=1) + 1)
        for j in range(n):
            for i in range(m - S):
                assert t_arrive[i + S, j] > t_bwd[i, j], (
                    f"stash slot clobber: micro-batch {i + S} arrives at "
                    f"stage {j} (t={t_arrive[i + S, j]}) before micro-batch "
                    f"{i}'s backward reads the slot (t={t_bwd[i, j]}); "
                    f"stash_slots={S} is too small for this table")


@dataclasses.dataclass(frozen=True)
class ZeroBubbleSchedule(Schedule):
    """Zero-bubble pipeline schedule (ZB-H1 lineage, Qi et al. 2023 —
    beyond the reference, which only ships GPipe fill-drain).

    Backward splits into two table ops: **B** (``BWD``: input-gradient only
    — must ride the rigid one-hop-per-cycle reverse ring, exactly like the
    combined backward of :class:`OneFOneBSchedule`) and **W** (``WGRAD``:
    weight-gradient only — depends only on its own B, so it can be deferred
    into slots that would otherwise idle during fill and drain). With
    roughly equal F/B/W op costs the drain bubble fills completely: e.g.
    (m=8, n=4) per-op-slot bubble drops from 27.3% (1F1B counting B+W as
    two units in one slot) to 11.1% — ~2.4x less idle (the exact figures
    ``bubble()`` reports and ``test_zb_tables_verify_and_beat_1f1b_bubble``
    pins).

    Memory matches 1F1B's activation cap in steady state, plus the deferred
    window: stashed stage inputs live until their W (not their B) consumes
    them, and each deferred (i, j) parks one activation-sized cotangent
    from B to W (``wstash_slots``).

    Executor note (``parallel.scheduled``): with ``checkpoint='never'`` the
    stored vjp closure serves both B and W — XLA's dead-code elimination
    prunes the weight-grad matmuls from the B call and the input-grad
    matmuls from the W call, so total compute equals one combined backward
    split across two schedulable slots. Under recompute modes the vjp only
    exists once the forward has been re-run at B, so the executor computes
    the FULL backward there and the W slots carry no compute (d>1 dynamic
    path; the d=1 static path defers just the accumulation) — correct and
    recompute-once, but the bubble-filling premise is gone, and
    construction warns. Zero-bubble scheduling is designed for (and shines
    with) stored activations: pair with ``checkpoint='never'``.

    Measurement honesty: the win is the table's idle fraction, which pays
    off when per-cycle time is compute-dominated (real multi-chip). On the
    virtual-CPU test mesh the extra cycles' fixed machinery overhead
    outweighs it (measured ~16% slower than 1f1b at tiny scale) — the
    transparency tests assert correctness there; the bubble advantage is
    the verified table property.
    """

    name: str = "zb-h1"

    @property
    def splits_backward(self) -> bool:
        return True

    def cycles(self, m: int, n: int) -> List[List[Tuple[int, int]]]:
        raise NotImplementedError(
            "zb-h1 is a manual-executor schedule; it has no forward-only "
            "wavefront (use op_tables)")

    @functools.lru_cache(maxsize=64)
    def op_tables(self, m: int, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """Greedy constructor: reserve rigid B chains at the earliest
        collision-free seed, fill free slots with the deepest-dependency-
        ready forward, then with the oldest pending W; one op per (cycle,
        device)."""
        max_T = 6 * (m + n) + 8
        op = np.full((max_T, n), IDLE, np.int32)
        mbi = np.zeros((max_T, n), np.int32)
        t_fwd = np.full((m, n), -1)
        t_b = np.full((m, n), -1)
        t_w = np.full((m, n), -1)
        reserved: dict = {}

        def chain_free(t0):
            # B(i, j) at t0 + (n-1-j): seed at the last stage, one hop/cycle
            return all((t0 + (n - 1 - j), j) not in reserved
                       and t0 + (n - 1 - j) < max_T for j in range(n))

        def reserve_chain(t0, i):
            for j in range(n):
                reserved[(t0 + (n - 1 - j), j)] = i

        next_seed = 0
        for t in range(max_T):
            # seed B chains for micro-batches whose last-stage forward is done
            while next_seed < m and 0 <= t_fwd[next_seed, n - 1] < t:
                t0 = t
                while not chain_free(t0):
                    t0 += 1
                reserve_chain(t0, next_seed)
                next_seed += 1
            for j in range(n):
                if (t, j) in reserved:
                    i = reserved[(t, j)]
                    _place(op, mbi, t, j, BWD, i)
                    t_b[i, j] = t
                    continue
                # forward: lowest micro-batch with upstream done, capped so
                # stashed inputs stay 1F1B-bounded — an input lives until
                # its W here, so the cap counts F-done-W-pending
                placed = False
                in_flight = int(np.sum((t_fwd[:, j] >= 0) & (t_w[:, j] < 0)))
                if in_flight < self._in_flight_cap(m, n):
                    for i in range(m):
                        if t_fwd[i, j] >= 0:
                            continue
                        if j > 0 and not (0 <= t_fwd[i, j - 1] < t):
                            break  # FIFO per stage: earlier i must go first
                        _place(op, mbi, t, j, FWD, i)
                        t_fwd[i, j] = t
                        placed = True
                        break
                if placed:
                    continue
                # weight-grad: oldest micro-batch with B done, W pending
                for i in range(m):
                    if t_b[i, j] >= 0 and t_w[i, j] < 0 and t_b[i, j] < t:
                        _place(op, mbi, t, j, WGRAD, i)
                        t_w[i, j] = t
                        break
            if (t_w >= 0).all():
                return op[:t + 1], mbi[:t + 1]
        raise AssertionError(
            f"zb-h1 table construction did not converge (m={m}, n={n})")

    def _in_flight_cap(self, m: int, n: int) -> int:
        """Max forwards admitted per stage before their W retires (keeps
        stashed inputs 1F1B-bounded; the zb-h2 variant widens this)."""
        return min(m, n + 1)

    def _times(self, m: int, n: int):
        return _zb_times(*self.op_tables(m, n), m, n)

    def stash_slots(self, m: int, n: int) -> int:
        """Peak live stashed inputs per stage — live until W (not B)."""
        t_fwd, _, t_w = self._times(m, n)
        arrive = np.where(np.arange(n)[None, :] == 0, t_fwd,
                          np.roll(t_fwd, 1, axis=1) + 1)
        T = self.num_cycles(m, n)
        cap = 0
        for j in range(n):
            for t in range(T):
                cap = max(cap, int(np.sum((arrive[:, j] <= t)
                                          & (t <= t_w[:, j]))))
        return cap

    def wstash_slots(self, m: int, n: int) -> int:
        """Peak live deferred cotangents per stage (B -> W window)."""
        _, t_b, t_w = self._times(m, n)
        T = self.num_cycles(m, n)
        cap = 0
        for j in range(n):
            for t in range(T):
                cap = max(cap, int(np.sum((t_b[:, j] <= t)
                                          & (t <= t_w[:, j]))))
        return cap

    def num_cycles(self, m: int, n: int) -> int:
        return self.op_tables(m, n)[0].shape[0]

    def bubble(self, m: int, n: int) -> float:
        """Idle fraction of op slots: each (i, j) occupies THREE slots
        (F, B, W), so busy = 3mn of T*n."""
        T = self.num_cycles(m, n)
        return (T * n - 3 * m * n) / (T * n)


@dataclasses.dataclass(frozen=True)
class ZeroBubbleDeepSchedule(ZeroBubbleSchedule):
    """The zb-v-ish variant (ZB-H2 lineage, Qi et al. 2023): same greedy
    constructor and rigid B chains as :class:`ZeroBubbleSchedule`, but the
    per-stage in-flight cap widens from ``n + 1`` to ``2n - 1`` — extra
    forwards are admitted during warmup so the fill-side idle slots carry
    real F work, and their deferred Ws drain into the cooldown. The memory
    trade is explicit: ``stash_slots`` grows toward ``2n - 1`` stage
    inputs per device (vs 1F1B's ``n``), which is exactly the knob ZB-H2
    turns — trade activation memory for bubble. ``bubble()`` at (m=8,
    n=4) drops below zb-h1's 11.1% (the analytic model in
    ``obs/zb_model.py`` and ``test_zb_deep_*`` pin the ordering
    zb-h2 < zb-h1 < 1f1b)."""

    name: str = "zb-h2"

    def _in_flight_cap(self, m: int, n: int) -> int:
        return min(m, max(2 * n - 1, n + 1))


def _zb_times(op: np.ndarray, mbi: np.ndarray, m: int, n: int):
    """Reconstruct (t_fwd, t_b, t_w) from split-backward tables; asserts
    each (i, j) runs each op at most once. Shared by the slot-capacity math
    and the verifier so the op-code mapping cannot drift between them."""
    t_fwd = np.full((m, n), -1)
    t_b = np.full((m, n), -1)
    t_w = np.full((m, n), -1)
    for t in range(op.shape[0]):
        for j in range(n):
            tgt = {FWD: t_fwd, BWD: t_b, WGRAD: t_w}.get(int(op[t, j]))
            if tgt is None:
                continue
            assert tgt[mbi[t, j], j] == -1, (t, j)
            tgt[mbi[t, j], j] = t
    return t_fwd, t_b, t_w


def verify_zb_op_tables(op: np.ndarray, mbi: np.ndarray, m: int, n: int,
                        stash_slots: Optional[int] = None,
                        wstash_slots: Optional[int] = None) -> None:
    """Invariants for split-backward (B/W) tables: every (i, j) runs F, B
    and W exactly once; F order strict downstream; B chains step exactly one
    cycle per hop (unbuffered reverse ring); W strictly after its B; and the
    FIFO/capacity properties the executor's ring indexing relies on."""
    t_fwd, t_b, t_w = _zb_times(op, mbi, m, n)
    assert (t_fwd >= 0).all() and (t_b >= 0).all() and (t_w >= 0).all(), \
        "missing ops"
    for i in range(m):
        for j in range(n):
            assert t_b[i, j] > t_fwd[i, j], f"B before F at {(i, j)}"
            assert t_w[i, j] > t_b[i, j], f"W before B at {(i, j)}"
            if j + 1 < n:
                assert t_fwd[i, j] < t_fwd[i, j + 1], (i, j)
                assert t_b[i, j] == t_b[i, j + 1] + 1, (i, j)
    # FIFO per stage (ring slot indexing i % S needs monotone windows)
    for tt in (t_fwd, t_b, t_w):
        assert (np.diff(tt, axis=0) > 0).all(), "non-FIFO op order"
    arrive = np.where(np.arange(n)[None, :] == 0, t_fwd,
                      np.roll(t_fwd, 1, axis=1) + 1)
    if stash_slots is not None:
        S = stash_slots
        for j in range(n):
            for i in range(m - S):
                assert arrive[i + S, j] > t_w[i, j], \
                    f"stash slot clobber at stage {j}, mb {i}"
    if wstash_slots is not None:
        Wg = wstash_slots
        for j in range(n):
            for i in range(m - Wg):
                assert t_b[i + Wg, j] > t_w[i, j], \
                    f"wstash slot clobber at stage {j}, mb {i}"
    if stash_slots is not None and wstash_slots is not None:
        # Joint capacity: W freeing the stash is what keeps the combined
        # activation footprint (stashed inputs + parked cotangents) at
        # Sg + Wg activation-sized buffers. A table whose true joint peak
        # exceeded the declared slots would alias live values.
        joint = zb_joint_capacity(op, mbi, m, n)
        assert joint <= stash_slots + wstash_slots, (
            f"joint stash+park peak {joint} exceeds declared "
            f"stash_slots + wstash_slots = "
            f"{stash_slots} + {wstash_slots}")


def zb_joint_capacity(op: np.ndarray, mbi: np.ndarray, m: int,
                      n: int) -> int:
    """Peak simultaneous activation-sized live values per stage of a
    split-backward table: stashed stage inputs (live from arrival until
    their W — B alone does not free them, its W still reads the taps) plus
    parked B cotangents (live from B until W). This is the number the
    W op actually SHRINKS versus a hypothetical stash-to-last-read-at-B
    accounting with the full combined backward deferred: deferring only
    the weight-grad half parks one cotangent per in-flight micro-batch
    instead of holding a second full residual set."""
    t_fwd, t_b, t_w = _zb_times(op, mbi, m, n)
    arrive = np.where(np.arange(n)[None, :] == 0, t_fwd,
                      np.roll(t_fwd, 1, axis=1) + 1)
    T = op.shape[0]
    cap = 0
    for j in range(n):
        for t in range(T):
            live_stash = int(np.sum((arrive[:, j] <= t) & (t <= t_w[:, j])))
            live_park = int(np.sum((t_b[:, j] <= t) & (t < t_w[:, j])))
            cap = max(cap, live_stash + live_park)
    return cap


# ---------------------------------------------------------------------------
# Overlapped (software-pipelined) transport: comm slots shifted vs compute
# ---------------------------------------------------------------------------
#
# The serialized executors issue their boundary ppermutes at the END of each
# scan body, and the value is consumable one cycle later — comm sits on the
# critical path between producer and consumer cycles. Overlapped transport
# instead permutes the PREVIOUS cycle's packed boundary buffer at the START
# of a body (no data dependency on that body's compute), parks the arrival
# into a receive FIFO after the compute has read the old carry, and makes it
# readable one body later still. A value produced at cycle t is therefore
# first consumable at t + 2: every cross-stage edge costs ``hop`` (= 2)
# cycles, and a serialized table must be *re-timed* before it can drive the
# overlapped executor. The functions below are that retiming pass and its
# proof obligations.


def _times_by_code(op, mbi, grp, m, d, v):
    """``(t_fwd, t_bwd, t_w)[m, v*d]`` from op tables; ``grp=None`` reads a
    stage-major table (column p IS the stage, v == 1). Unscheduled ops stay
    ``-1``; each (op, i, s) may appear at most once."""
    S = v * d
    times = {FWD: np.full((m, S), -1), BWD: np.full((m, S), -1),
             WGRAD: np.full((m, S), -1)}
    for t in range(op.shape[0]):
        for p in range(op.shape[1]):
            code = int(op[t, p])
            if code == IDLE:
                continue
            s = (int(grp[t, p]) * d + p) if grp is not None else p
            i = int(mbi[t, p])
            assert times[code][i, s] == -1, (t, p)
            times[code][i, s] = t
    return times[FWD], times[BWD], times[WGRAD]


def shift_comm_tables(op, mbi, grp=None, *, m: int, d: int, v: int = 1,
                      hop: int = 2):
    """Re-time a verified serialized table to the overlapped-transport
    contract; returns ``(op, mb, grp)`` device tables.

    Ops are visited in (cycle, device) order — every dependency's producer
    has a strictly smaller original cycle, so one in-order pass suffices —
    and each is assigned the earliest cycle satisfying:

    * one op per device per cycle, in the ORIGINAL per-device order
      (order preservation makes the pass collision-free by construction and
      keeps each device's accumulation order, hence bitwise results,
      identical to the serialized run);
    * ``FWD(i, s) >= FWD(i, s-1) + hop`` — the activation parked from the
      packed forward buffer is readable ``hop`` cycles after its send;
    * ``BWD(i, s) >= BWD(i, s+1) + hop`` — the reverse ring becomes
      *elastic*: cotangents land in a grad-park FIFO instead of being
      consumed in place, so the rigid ``== + 1`` chain relaxes to an
      inequality;
    * ``BWD(i, s) > FWD(i, s)`` and ``WGRAD(i, s) > BWD(i, s)``.

    ``d == 1`` has no transport and returns the input unchanged (plus a
    zero ``grp`` if none was given).
    """
    grp_in = grp if grp is not None else np.zeros_like(op)
    if d <= 1 or hop <= 1:
        return op.copy(), mbi.copy(), grp_in.copy()
    S = v * d
    times = {FWD: np.full((m, S), -1), BWD: np.full((m, S), -1),
             WGRAD: np.full((m, S), -1)}
    last = np.full(op.shape[1], -1, np.int64)
    events = []
    for t in range(op.shape[0]):
        for p in range(op.shape[1]):
            code = int(op[t, p])
            if code == IDLE:
                continue
            i = int(mbi[t, p])
            g = int(grp_in[t, p])
            s = g * d + p
            lo = int(last[p]) + 1
            if code == FWD:
                if s > 0:
                    lo = max(lo, int(times[FWD][i, s - 1]) + hop)
            elif code == BWD:
                lo = max(lo, int(times[FWD][i, s]) + 1)
                if s + 1 < S:
                    lo = max(lo, int(times[BWD][i, s + 1]) + hop)
            else:  # WGRAD
                lo = max(lo, int(times[BWD][i, s]) + 1)
            times[code][i, s] = lo
            last[p] = lo
            events.append((lo, p, code, i, g))
    T2 = int(last.max()) + 1
    op2 = np.full((T2, op.shape[1]), IDLE, np.int32)
    mbi2 = np.zeros((T2, op.shape[1]), np.int32)
    grp2 = np.zeros((T2, op.shape[1]), np.int32)
    for t2, p, code, i, g in events:
        op2[t2, p], mbi2[t2, p], grp2[t2, p] = code, i, g
    return op2, mbi2, grp2


def _check_overlap_windows(arrive, read, K: int, what: str) -> None:
    """Slot-clobber proof under park-after-compute semantics: value ``a``
    parked at ``arrive[a]`` into slot ``a % K`` must survive through its
    last read ``read[a]`` (a read at cycle t sees parks <= t - 1, so a park
    AT the read cycle is safe). ``arrive < 0`` marks entries with no
    arrival (e.g. stage 0) and is skipped."""
    m = len(arrive)
    for a in range(m):
        if arrive[a] < 0:
            continue
        for b in range(m):
            if b == a or arrive[b] < 0 or a % K != b % K:
                continue
            assert not (arrive[a] <= arrive[b] <= read[a] - 1), (
                f"{what}: slot clobber — value {a} (parked t={arrive[a]}, "
                f"last read t={read[a]}) overwritten by value {b} at "
                f"t={arrive[b]} with {K} slots")


def overlap_fifo_capacity(arrive, read) -> int:
    """Smallest slot count K (slots ``i % K``) passing
    :func:`_check_overlap_windows` for the given arrival/last-read cycles.
    Makes no monotonicity assumption — GPipe's backward drains micro-batches
    in DECREASING order, so grad-park arrivals are not FIFO in i."""
    return overlap_joint_capacity([(arrive, read)], len(arrive))


def overlap_joint_capacity(windows, m: int) -> int:
    """Smallest K valid SIMULTANEOUSLY for every ``(arrive, read)`` window
    set in ``windows``. The executor uses one slot count across all virtual
    stages and park uses (slot ``g*K + i % K``), and clobber-freedom is not
    monotone in K (``i % K`` sharing reshuffles as K grows), so the joint
    minimum must be searched, not maxed over per-stage minima. ``K = m``
    always passes (every micro-batch gets its own slot)."""
    for K in range(1, m + 1):
        try:
            for arrive, read in windows:
                _check_overlap_windows(arrive, read, K, "probe")
        except AssertionError:
            continue
        return K
    return m


def verify_shifted_op_tables(op, mbi, grp=None, *, m: int, d: int,
                             v: int = 1, hop: int = 2,
                             splits_backward: bool = False,
                             stash_slots: Optional[int] = None,
                             grad_slots: Optional[int] = None,
                             wstash_slots: Optional[int] = None) -> None:
    """Prove an overlapped-transport table: every receive lands before its
    consumer reads it, for any of the four schedule families (gpipe, 1f1b,
    interleaved-1f1b via ``grp``/``v``, zb-h1 via ``splits_backward``).

    Timing model (see module comment): a boundary value produced at cycle t
    is permuted at t + 1 and parked after that body's compute, so its first
    legal read is t + 2 (= ``hop``). Checks:

    * each (i, s) runs FWD and BWD (and WGRAD iff ``splits_backward``)
      exactly once, on the right device (``s % d``), one op per device per
      cycle (table shape);
    * ``FWD(i, s+1) >= FWD(i, s) + hop`` and ``BWD(i, s) >= BWD(i, s+1) +
      hop`` — no consumer reads a value still in flight;
    * ``BWD > FWD`` and ``WGRAD > BWD`` per (i, s);
    * with capacities given, the park FIFOs never clobber a live value:
      activations (arrive ``FWD(i, s-1) + 1``, last read = the micro-batch's
      last op at s — conservative across recompute modes), grad park
      (arrive ``BWD(i, s+1) + 1``, read at ``BWD(i, s)``), and the local
      B→W cotangent stash for split-backward tables.
    """
    S = v * d
    t_f, t_b, t_w = _times_by_code(op, mbi, grp, m, d, v)
    assert (t_f >= 0).all() and (t_b >= 0).all(), "missing ops"
    if splits_backward:
        assert (t_w >= 0).all(), "missing W ops"
    for i in range(m):
        for s in range(S):
            assert t_b[i, s] > t_f[i, s], f"B before F at {(i, s)}"
            if splits_backward:
                assert t_w[i, s] > t_b[i, s], f"W before B at {(i, s)}"
            if s + 1 < S:
                assert t_f[i, s + 1] >= t_f[i, s] + hop, (
                    f"shifted comm slot violation: FWD({i},{s + 1}) at "
                    f"t={t_f[i, s + 1]} consumes an activation sent at "
                    f"t={t_f[i, s]} that is still in flight "
                    f"(hop={hop})")
                assert t_b[i, s] >= t_b[i, s + 1] + hop, (
                    f"shifted comm slot violation: BWD({i},{s}) at "
                    f"t={t_b[i, s]} consumes a gradient sent at "
                    f"t={t_b[i, s + 1]} that is still in flight "
                    f"(hop={hop})")
    read_last = np.maximum(t_f, np.maximum(t_b, t_w))
    for s in range(S):
        if stash_slots is not None and s > 0:
            _check_overlap_windows(t_f[:, s - 1] + 1, read_last[:, s],
                                   stash_slots, f"stash (stage {s})")
        if grad_slots is not None and s + 1 < S:
            _check_overlap_windows(t_b[:, s + 1] + 1, t_b[:, s],
                                   grad_slots, f"grad park (stage {s})")
        if wstash_slots is not None and splits_backward:
            _check_overlap_windows(t_b[:, s], t_w[:, s],
                                   wstash_slots, f"wstash (stage {s})")


# ---------------------------------------------------------------------------
# Phase compiler: warmup / steady-state / cooldown segmentation of op tables
# ---------------------------------------------------------------------------
#
# The scan-based executors interpret the op tables per cycle: every body
# carries a lax.switch over the op code plus sentinel-masked stores for the
# branches not taken. The phase compiler removes that interpreter overhead
# by compiling the table's STRUCTURE into the program: it re-times the
# serialized table so that at every cycle all devices run the SAME op code
# (cycle-uniformity — the only form of per-cycle specialization a single
# shard_map trace can express without dynamic dispatch), then segments the
# result into short warmup/cooldown ramps (unrolled straight-line, partial
# idles masked by data selects) and maximal dense periodic steady-state
# windows (a fixed-body lax.scan whose body is the period's concrete op
# sequence — no switch, no masked no-ops: every device is busy every cycle).
#
# Bitwise contract: the aligner may change how F and B ops INTERLEAVE on a
# device (the serialized 1F1B in-flight window is provably too small for
# the hop-2 transport latency — keeping its total order forces steady-state
# stalls), but it preserves each (stage, op-code) stream's order. F ops and
# B/W ops touch disjoint accumulators (loss/stats vs grads), so preserving
# per-code order per stage preserves every accumulation order — results
# stay bitwise identical to the interpreted executor on the original table.

#: Residue classes per op code used by the alignment retimer: scheduling
#: each code only on its own residue (mod the class modulus) makes steady
#: state cycle-uniform by construction. ``PHASE_KLASS_FB`` alternates
#: all-F / all-B cycles (period 2: 1f1b lineage); ``PHASE_KLASS_FBW``
#: rotates all-F / all-B / all-W (period 3: split-backward lineage).
PHASE_KLASS_FB = {FWD: (0, 2), BWD: (1, 2)}
PHASE_KLASS_FBW = {FWD: (0, 3), BWD: (1, 3), WGRAD: (2, 3)}


def align_phase_tables(op, mbi, grp=None, *, m: int, d: int, v: int = 1,
                       hop: int = 2, klass=None,
                       priority=(BWD, WGRAD, FWD)):
    """Re-time a serialized table for the overlapped-transport contract via
    time-stepped list scheduling; returns ``(op, mb, grp)`` device tables.

    Unlike :func:`shift_comm_tables` (which preserves each device's TOTAL
    op order and therefore inherits the serialized schedule's in-flight
    window — too small for hop-2 latency, leaving idle holes in steady
    state), this pass preserves only each device's PER-CODE op order (the
    bitwise-parity invariant, see module comment above) and re-derives the
    interleaving: at each cycle every device issues the highest-priority
    code whose residue class (``klass``) admits the cycle and whose queue
    head is dependency-ready under the hop-latency contract

    * ``FWD(i, s)  >= FWD(i, s-1) + hop``
    * ``BWD(i, s)  >= BWD(i, s+1) + hop`` and ``> FWD(i, s)``
    * ``WGRAD(i, s) > BWD(i, s)``

    Default ``priority`` drains backwards eagerly, which caps the live
    stash window at O(d·v·hop) without an explicit in-flight limit.
    """
    grp_in = grp if grp is not None else np.zeros_like(op)
    S = v * d
    q = {c: [[] for _ in range(d)] for c in (FWD, BWD, WGRAD)}
    for t in range(op.shape[0]):
        for p in range(op.shape[1]):
            c = int(op[t, p])
            if c == IDLE:
                continue
            q[c][p].append((int(mbi[t, p]), int(grp_in[t, p]) * d + p))
    times = {FWD: np.full((m, S), -1), BWD: np.full((m, S), -1),
             WGRAD: np.full((m, S), -1)}
    head = {c: [0] * d for c in (FWD, BWD, WGRAD)}
    events = []
    n_total = sum(len(q[c][p]) for c in q for p in range(d))
    n_done = 0
    max_T = (hop + 1) * (op.shape[0] + 4) + 8
    for t in range(max_T):
        if n_done == n_total:
            break
        for p in range(d):
            for c in priority:
                h = head[c][p]
                if h >= len(q[c][p]):
                    continue
                if klass is not None:
                    r, M = klass.get(c, (0, 1))
                    if (t % M) != r:
                        continue
                i, s = q[c][p][h]
                if c == FWD:
                    if s > 0 and not (0 <= times[FWD][i, s - 1] <= t - hop):
                        continue
                elif c == BWD:
                    if not (0 <= times[FWD][i, s] < t):
                        continue
                    if s + 1 < S and not (0 <= times[BWD][i, s + 1]
                                          <= t - hop):
                        continue
                else:
                    if not (0 <= times[BWD][i, s] < t):
                        continue
                times[c][i, s] = t
                head[c][p] = h + 1
                events.append((t, p, c, i, s // d))
                n_done += 1
                break
    if n_done != n_total:
        raise AssertionError(
            f"phase alignment did not converge ({n_done}/{n_total} ops "
            f"placed in {max_T} cycles; klass={klass})")
    T2 = max(e[0] for e in events) + 1
    op2 = np.full((T2, d), IDLE, np.int32)
    mb2 = np.zeros((T2, d), np.int32)
    gr2 = np.zeros((T2, d), np.int32)
    for t2, p, c, i, g in events:
        op2[t2, p], mb2[t2, p], gr2[t2, p] = c, i, g
    return op2, mb2, gr2


@dataclasses.dataclass(frozen=True)
class PhaseSegment:
    """One compiled phase: cycles ``[t0, t1)`` of the aligned table.

    ``kind == 'unroll'``: ramp cycles, lowered to straight-line code (each
    cycle's single op code is a trace-time constant; devices idle at a
    cycle are masked by data selects into sentinel slots). ``kind ==
    'scan'``: a dense periodic steady-state window — ``(t1 - t0) //
    period`` iterations of the fixed ``codes`` body, every device busy
    every cycle."""
    kind: str
    t0: int
    t1: int
    period: int = 0
    codes: Tuple[int, ...] = ()

    @property
    def cycles(self) -> int:
        return self.t1 - self.t0

    @property
    def iters(self) -> int:
        return (self.t1 - self.t0) // self.period if self.period else 0


@dataclasses.dataclass(frozen=True)
class PhaseProgram:
    """Accepted phase compilation: aligned tables + segmentation."""
    op: np.ndarray
    mbi: np.ndarray
    grp: np.ndarray
    segments: Tuple[PhaseSegment, ...]
    policy: str                    # which klass/priority candidate won
    cycle_codes: Tuple[int, ...]   # per-cycle uniform op code (IDLE ok)
    dense: Tuple[bool, ...]        # per-cycle: True = no device idles

    @property
    def cycles(self) -> int:
        return int(self.op.shape[0])

    @property
    def unrolled_cycles(self) -> int:
        return sum(s.cycles for s in self.segments if s.kind == "unroll")

    @property
    def scan_cycles(self) -> int:
        return sum(s.cycles for s in self.segments if s.kind == "scan")


@dataclasses.dataclass(frozen=True)
class PhaseVerdict:
    """Accept/reject result of :func:`compile_phases`. Rejected tables run
    on the interpreted executor (the caller falls back loudly)."""
    accepted: bool
    reason: str
    program: Optional[PhaseProgram] = None


def _phase_cycle_summary(op, d):
    """Per-cycle ``(code, dense)``: the single non-idle op code (None when
    codes are mixed — not phase-compilable) and whether no device idles."""
    out = []
    for t in range(op.shape[0]):
        codes = {int(op[t, p]) for p in range(d)}
        nonidle = codes - {IDLE}
        if len(nonidle) > 1:
            out.append((None, False))
        elif not nonidle:
            out.append((IDLE, False))
        else:
            out.append((nonidle.pop(), IDLE not in codes))
    return out


def segment_phases(op, d, *, min_reps: int = 2,
                   max_period: int = 6) -> Optional[Tuple[PhaseSegment, ...]]:
    """Segment an aligned table into unroll ramps and dense periodic scan
    windows. Returns None when any cycle mixes op codes across devices
    (no single shard_map trace can specialize it without dispatch)."""
    summary = _phase_cycle_summary(op, d)
    if any(c is None for c, _ in summary):
        return None
    T = len(summary)
    segments: List[PhaseSegment] = []
    t = 0
    pend_unroll = 0
    while t < T:
        if not summary[t][1]:
            pend_unroll += 1
            t += 1
            continue
        t1 = t
        while t1 < T and summary[t1][1]:
            t1 += 1
        codes = [summary[k][0] for k in range(t, t1)]
        best = None
        for P in range(1, min(max_period, len(codes)) + 1):
            if len(codes) // P < min_reps:
                break
            if all(codes[k] == codes[k % P] for k in range(len(codes))):
                best = P
                break
        if best is None:
            pend_unroll += t1 - t
            t = t1
            continue
        n_iters = len(codes) // best
        t_scan_end = t + n_iters * best
        if pend_unroll:
            segments.append(PhaseSegment("unroll", t - pend_unroll, t))
            pend_unroll = 0
        segments.append(PhaseSegment("scan", t, t_scan_end, period=best,
                                     codes=tuple(codes[:best])))
        pend_unroll = t1 - t_scan_end
        t = t1
    if pend_unroll:
        segments.append(PhaseSegment("unroll", T - pend_unroll, T))
    return tuple(segments)


def _per_code_stage_order(op, mbi, grp, d):
    """Per (virtual stage, code) micro-batch order — the accumulation
    orders that must survive alignment for bitwise parity."""
    order: dict = {}
    for t in range(op.shape[0]):
        for p in range(op.shape[1]):
            c = int(op[t, p])
            if c == IDLE:
                continue
            g = int(grp[t, p]) if grp is not None else 0
            order.setdefault((g * d + p, c), []).append(int(mbi[t, p]))
    return order


def compile_phases(op, mbi, grp=None, *, m: int, d: int, v: int = 1,
                   hop: int = 2, max_unroll: Optional[int] = None,
                   max_period: int = 6) -> PhaseVerdict:
    """Phase-compile a SERIALIZED op table (the universal schedule
    currency, see :func:`verify_op_tables`): try the alignment policies,
    verify each result against the overlapped-transport invariants
    (:func:`verify_shifted_op_tables` — the ``comm_shift`` contract) and
    the per-code order-preservation guarantee, segment it, and return the
    best accepted :class:`PhaseVerdict`.

    Acceptance requires the unrolled ramps to stay short (``max_unroll``,
    default ``8·d·v + 4·hop + 8`` — O(stages), so trace size does not grow
    with m) — a table with no usable steady window on a large m rejects
    rather than unrolling unboundedly. ``d == 1`` rejects (the static
    unroll path already specializes single-device tables at trace time)."""
    if d <= 1:
        return PhaseVerdict(False, "d == 1: no transport to phase "
                            "(static unroll already specializes)")
    if max_unroll is None:
        max_unroll = 8 * d * v + 4 * hop + 8
    splits = bool((np.asarray(op) == WGRAD).any())
    candidates = []
    if splits:
        candidates.append(("fbw3", PHASE_KLASS_FBW, (BWD, WGRAD, FWD)))
        candidates.append(("none", None, (BWD, WGRAD, FWD)))
    else:
        candidates.append(("fb2", PHASE_KLASS_FB, (BWD, WGRAD, FWD)))
        candidates.append(("none-ffirst", None, (FWD, BWD, WGRAD)))
        candidates.append(("none", None, (BWD, WGRAD, FWD)))
    want_order = _per_code_stage_order(op, mbi, grp, d)
    best = None
    reasons = []
    for name, klass, prio in candidates:
        try:
            op2, mb2, gr2 = align_phase_tables(
                op, mbi, grp, m=m, d=d, v=v, hop=hop, klass=klass,
                priority=prio)
            verify_shifted_op_tables(
                op2, mb2, gr2 if (grp is not None or v > 1) else None,
                m=m, d=d, v=v, hop=hop, splits_backward=splits)
            got_order = _per_code_stage_order(op2, mb2, gr2, d)
            if got_order != want_order:
                raise AssertionError("per-code stage order changed")
        except AssertionError as e:
            reasons.append(f"{name}: {e}")
            continue
        segments = segment_phases(op2, d, max_period=max_period)
        if segments is None:
            reasons.append(f"{name}: mixed-code cycles survive alignment")
            continue
        prog = PhaseProgram(
            op2, mb2, gr2, segments, name,
            tuple(c for c, _ in _phase_cycle_summary(op2, d)),
            tuple(dn for _, dn in _phase_cycle_summary(op2, d)))
        if prog.unrolled_cycles > max_unroll:
            reasons.append(
                f"{name}: {prog.unrolled_cycles} unrolled cycles exceed "
                f"the {max_unroll}-cycle ramp budget")
            continue
        score = (prog.scan_cycles / max(prog.cycles, 1), -prog.cycles)
        if best is None or score > best[0]:
            best = (score, prog)
    if best is None:
        return PhaseVerdict(False, "; ".join(reasons) or "no candidates")
    return PhaseVerdict(True, f"policy {best[1].policy}", best[1])


_SCHEDULES = {
    "gpipe": GPipeSchedule,
    "1f1b": OneFOneBSchedule,
    "interleaved": InterleavedSchedule,
    "interleaved-1f1b": InterleavedOneFOneBSchedule,
    "zb-h1": ZeroBubbleSchedule,
    "zb-h2": ZeroBubbleDeepSchedule,
}


def get_schedule(name: str, **kwargs) -> Schedule:
    if name not in _SCHEDULES:
        raise ValueError(f"unknown schedule {name!r}; options: {sorted(_SCHEDULES)}")
    return _SCHEDULES[name](**kwargs)


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """A verified degraded-topology plan produced by
    :func:`replan_stage_loss`: the survivor stage count, the re-cut
    layer balance (None when the caller gave no balance to re-cut),
    freshly emitted + verified op tables for the new width, and the
    phase-compiler verdict (advisory — a table that phase-rejects still
    executes on the generic path)."""

    n_stages: int
    balance: Optional[Tuple[int, ...]]
    op: np.ndarray
    mbi: np.ndarray
    phase: "PhaseVerdict"


def replan_stage_loss(m: int, n_stages: int, lost_stage: int, *,
                      schedule: str = "1f1b",
                      balance: Optional[List[int]] = None,
                      costs: Optional[List[float]] = None,
                      hop: int = 2) -> ElasticPlan:
    """Re-plan a pipeline after losing one stage: emit, verify, and
    phase-compile the op table for the surviving ``n_stages - 1`` width.

    This is the schedules-as-data payoff the elastic controller rides:
    the schedule family regenerates its table for ANY stage count, so
    recovery is a fresh emission plus the same proofs every table must
    pass (:func:`verify_op_tables` with the schedule's own stash/wstash
    capacities) — not a hand-patched topology. ``balance``/``costs``
    re-cut the layer assignment via
    :func:`~pipe_tpu.core.balance.rebalance_stage_loss`. Raises
    ``ValueError`` when no survivor topology exists (n_stages < 2, a
    lost stage out of range, or an interleaved schedule — re-plan those
    as their v=1 base family first).
    """
    if n_stages < 2:
        raise ValueError(
            f"cannot re-plan stage loss with n_stages={n_stages}: "
            f"no survivor topology exists")
    if not 0 <= lost_stage < n_stages:
        raise ValueError(
            f"lost_stage={lost_stage} out of range for {n_stages} stages")
    sched = get_schedule(schedule)
    if sched.v != 1:
        raise ValueError(
            f"schedule {schedule!r} interleaves v={sched.v} virtual "
            f"stages; re-plan via its v=1 base family")
    n_new = n_stages - 1
    op, mbi = sched.op_tables(m, n_new)
    verify_op_tables(op, mbi, m, n_new,
                     stash_slots=sched.stash_slots(m, n_new),
                     wstash_slots=(sched.wstash_slots(m, n_new)
                                   if sched.splits_backward else None))
    new_balance = None
    if balance is not None:
        from .balance import rebalance_stage_loss
        new_balance = tuple(rebalance_stage_loss(balance, costs))
    phase = compile_phases(op, mbi, m=m, d=n_new, hop=hop)
    return ElasticPlan(n_stages=n_new, balance=new_balance,
                       op=op, mbi=mbi, phase=phase)
