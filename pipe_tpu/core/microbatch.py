"""Micro-batch scatter/gather: split a mini-batch into micro-batches and back.

Capability parity with ``torch.distributed.pipeline.sync.microbatch`` (cited via its
import sites, reference ``pipe.py:17,452-464,477-490`` and the quoted module at
``README.md:316-322``), redesigned for JAX:

* ``scatter`` follows ``torch.chunk`` semantics on dim 0 — chunk size is
  ``ceil(n / chunks)`` so the call may yield *fewer* than ``chunks`` micro-batches
  and the last one may be smaller (the off-by-one interaction with
  ``checkpoint_stop`` flagged at reference ``README.md:398`` is handled by the
  caller recomputing ``checkpoint_stop`` against ``len(batches)``).
* Non-array leaves and arrays wrapped in :class:`NoChunk` are replicated into every
  micro-batch rather than split (reference ``pipe.py:462-464``).
* ``gather`` concatenates arrays per position; non-array positions are taken from
  the first micro-batch (they were replicated by ``scatter``).

For the *compiled* SPMD pipeline path there are also stacked forms,
:func:`stack_scatter` / :func:`stack_gather`, which produce a single
``[chunks, mb, ...]`` leading-axis layout (static shapes, XLA-friendly) with an
explicit validity count for non-divisible batches.
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "NoChunk",
    "Batch",
    "check",
    "scatter",
    "gather",
    "stack_scatter",
    "stack_gather",
]

ArrayTypes = (jax.Array, np.ndarray)


def is_array(value: Any) -> bool:
    """True for concrete or traced JAX arrays and numpy arrays."""
    return isinstance(value, ArrayTypes) or isinstance(value, jax.core.Tracer)


class NoChunk:
    """Wrap an array to exclude it from scatter's dim-0 split.

    The wrapped array is replicated to every micro-batch whole (reference
    ``pipe.py:462-464``). The wrapper exists only at the API boundary; inside a
    :class:`Batch` the raw array is stored.
    """

    __slots__ = ("_value",)

    def __init__(self, value):
        if not is_array(value):
            raise TypeError(f"NoChunk expects an array, got {type(value).__name__}")
        self._value = value

    @property
    def value(self):
        return self._value

    def __repr__(self) -> str:
        return f"NoChunk({self._value!r})"


class Batch:
    """One micro-batch: an immutable tuple of values with helpers.

    Mirrors the reference ``Batch`` container (``README.md:316-322``): ``atomic``
    marks the single-tensor fast path, :meth:`call` applies a function to the
    payload, and indexing/slicing address positional values.
    """

    __slots__ = ("_values", "atomic", "replicated")

    def __init__(self, values: Union[Any, Tuple[Any, ...]], atomic: bool = False,
                 replicated: Tuple[int, ...] = ()):
        if atomic:
            self._values = (values,)
        else:
            self._values = tuple(values)
        self.atomic = atomic
        # Positions holding replicated (NoChunk / non-array) values: gather
        # takes them from one micro-batch instead of concatenating.
        self.replicated = tuple(replicated)

    @property
    def values(self) -> Tuple[Any, ...]:
        return self._values

    @property
    def tensor(self):
        """The sole array of an atomic batch (reference Batch.tensor)."""
        if not self.atomic:
            raise AttributeError("not an atomic batch; use .values / .tensors")
        return self._values[0]

    @property
    def tensors(self) -> Tuple[Any, ...]:
        if self.atomic:
            raise AttributeError("atomic batch; use .tensor")
        return self._values

    def call(self, function: Callable) -> "Batch":
        """Apply ``function`` to the payload, preserving atomicity when possible.

        Atomic batches call ``function(tensor)``; non-atomic call
        ``function(*values)``. A tuple/list result becomes a non-atomic batch, a
        single value an atomic one — matching the reference's partition-call
        contract (``README.md:316-322``).
        """
        if self.atomic:
            result = function(self._values[0])
        else:
            result = function(*self._values)
        if isinstance(result, (tuple, list)):
            # Replication marks do NOT survive a transform: a stage may permute
            # or overwrite positions, so carrying marks forward could make
            # gather silently drop real per-microbatch outputs. Marks only
            # matter for a direct scatter -> gather round trip.
            return Batch(tuple(result), atomic=False)
        return Batch(result, atomic=True)

    def find_tensor_idx(self) -> int:
        """Index of the first array value (reference Batch.find_tensor_idx)."""
        for i, v in enumerate(self._values):
            if is_array(v):
                return i
        raise ValueError("no array in batch")

    def __len__(self) -> int:
        return len(self._values)

    def __iter__(self):
        return iter(self._values)

    def __getitem__(self, index):
        if isinstance(index, slice):
            return Batch(self._values[index], atomic=False)
        return self._values[index]

    def with_value(self, index: int, value) -> "Batch":
        """Functional update of position ``index`` (JAX-style, no mutation)."""
        values = list(self._values)
        values[index] = value
        return Batch(tuple(values), atomic=self.atomic and len(values) == 1)

    def __repr__(self) -> str:
        return f"Batch({self._values!r}, atomic={self.atomic})"


def check(*inputs: Any) -> None:
    """Validate pipeline inputs: at least one array among them.

    Mirrors reference ``microbatch.check`` called from ``Pipe.forward``
    (``pipe.py:476-477``). Device checking is meaningless under SPMD/jit and is
    intentionally dropped.
    """
    if not inputs:
        raise TypeError("no input provided")
    for x in inputs:
        if is_array(x) or isinstance(x, NoChunk):
            return
    raise TypeError("expected at least one array as input")


def _chunk_sizes(n: int, chunks: int) -> List[int]:
    """``torch.chunk`` split sizes: ceil-sized chunks, possibly fewer than asked."""
    if chunks <= 0:
        raise ValueError("number of chunks must be positive")
    size = math.ceil(n / chunks)
    if size == 0:
        return [n]
    sizes = []
    remaining = n
    while remaining > 0:
        take = min(size, remaining)
        sizes.append(take)
        remaining -= take
    return sizes or [0]


def scatter(inputs: Sequence[Any], chunks: int) -> List[Batch]:
    """Split each array input along dim 0 into micro-batches.

    Reference semantics (``pipe.py:484``; ``README.md:316-322``): array inputs are
    split with ``torch.chunk`` sizing; ``NoChunk``-wrapped arrays and non-array
    values are replicated whole. All split inputs must agree on batch size.
    Returns a list of :class:`Batch`; its length may be < ``chunks``.
    """
    if isinstance(inputs, Batch):
        raise TypeError("scatter takes raw inputs, not a Batch")
    inputs = tuple(inputs)
    check(*inputs)

    batch_size = None
    for x in inputs:
        if is_array(x):
            if x.ndim == 0:
                raise ValueError("cannot scatter a 0-d array; wrap it in NoChunk")
            if batch_size is None:
                batch_size = x.shape[0]
            elif x.shape[0] != batch_size:
                raise ValueError(
                    f"inconsistent batch sizes: {batch_size} vs {x.shape[0]}"
                )
    if batch_size is None:
        # Only NoChunk/non-array inputs: replicate into exactly `chunks` batches.
        sizes = [None] * chunks
    else:
        sizes = _chunk_sizes(batch_size, chunks)

    atomic = len(inputs) == 1 and is_array(inputs[0])

    per_chunk: List[List[Any]] = [[] for _ in sizes]
    replicated: List[int] = []
    for pos, x in enumerate(inputs):
        if isinstance(x, NoChunk):
            replicated.append(pos)
            for vals in per_chunk:
                vals.append(x.value)
        elif is_array(x):
            offset = 0
            for k, sz in enumerate(sizes):
                per_chunk[k].append(jax.lax.slice_in_dim(x, offset, offset + sz, axis=0)
                                    if isinstance(x, jax.core.Tracer)
                                    else x[offset:offset + sz])
                offset += sz
        else:
            replicated.append(pos)
            for vals in per_chunk:
                vals.append(x)

    if atomic:
        return [Batch(vals[0], atomic=True) for vals in per_chunk]
    rep = tuple(replicated)
    return [Batch(tuple(vals), atomic=False, replicated=rep)
            for vals in per_chunk]


def gather(batches: Sequence[Batch]):
    """Concatenate micro-batches back into a mini-batch (reference ``pipe.py:490``).

    Array positions are concatenated along dim 0; non-array positions (replicated
    by scatter) are taken from the first batch. Returns a single value for atomic
    batches, else a tuple.
    """
    if not batches:
        raise ValueError("no batches to gather")
    first = batches[0]
    if first.atomic:
        return jnp.concatenate([b.tensor for b in batches], axis=0)
    outputs = []
    for i in range(len(first)):
        if is_array(first[i]) and i not in first.replicated:
            outputs.append(jnp.concatenate([b[i] for b in batches], axis=0))
        else:
            outputs.append(first[i])
    return tuple(outputs)


# ---------------------------------------------------------------------------
# Stacked (compiled-path) forms
# ---------------------------------------------------------------------------

def stack_scatter(tree: Any, chunks: int) -> Tuple[Any, int]:
    """Reshape every array leaf ``[n, ...] -> [chunks, n/chunks, ...]``.

    The XLA-friendly scatter used inside compiled pipelines: one static-shaped
    stacked layout instead of a Python list of slices. Leaves whose dim 0 is not
    divisible by ``chunks`` are right-padded with zeros; the caller receives the
    true batch size to mask with. ``NoChunk`` leaves are broadcast to a leading
    ``chunks`` axis.
    """
    if chunks <= 0:
        raise ValueError("number of chunks must be positive")

    batch_size = None
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=lambda x: isinstance(x, NoChunk)):
        if isinstance(leaf, NoChunk):
            continue
        if is_array(leaf):
            batch_size = leaf.shape[0] if batch_size is None else batch_size
            if leaf.shape[0] != batch_size:
                raise ValueError("inconsistent batch sizes in stack_scatter")
    if batch_size is None:
        raise TypeError("stack_scatter needs at least one splittable array leaf")

    mb = math.ceil(batch_size / chunks)
    padded = mb * chunks

    def split(leaf):
        if isinstance(leaf, NoChunk):
            return jnp.broadcast_to(leaf.value, (chunks,) + leaf.value.shape)
        if not is_array(leaf):
            return leaf
        x = leaf
        if padded != batch_size:
            pad = [(0, padded - batch_size)] + [(0, 0)] * (x.ndim - 1)
            x = jnp.pad(x, pad)
        return jnp.reshape(x, (chunks, mb) + x.shape[1:])

    stacked = jax.tree_util.tree_map(
        split, tree, is_leaf=lambda x: isinstance(x, NoChunk)
    )
    return stacked, batch_size


def valid_row_mask(stacked: Any, batch_size: int):
    """``[chunks, mb_rows]`` float mask of real rows in a stacked batch.

    Owns the padding-layout knowledge: :func:`stack_scatter` pads at the
    TAIL of the flattened batch, so row ``(c, r)`` is real iff its flat
    index ``c * mb_rows + r`` is below the true batch size. Weight losses
    with it so zero-padded rows never contaminate loss or gradients.
    """
    import jax.numpy as jnp

    chunks_n, mb_rows = jax.tree_util.tree_leaves(stacked)[0].shape[:2]
    idx = jnp.arange(chunks_n * mb_rows).reshape(chunks_n, mb_rows)
    return (idx < batch_size).astype(jnp.float32)


def stack_gather(tree: Any, batch_size: int) -> Any:
    """Inverse of :func:`stack_scatter`: ``[chunks, mb, ...] -> [n, ...]``.

    Drops any zero padding introduced for non-divisible batch sizes.
    """

    def merge(leaf):
        if not is_array(leaf):
            return leaf
        merged = jnp.reshape(leaf, (leaf.shape[0] * leaf.shape[1],) + leaf.shape[2:])
        if merged.shape[0] != batch_size:
            merged = jax.lax.slice_in_dim(merged, 0, batch_size, axis=0)
        return merged

    return jax.tree_util.tree_map(merge, tree)
