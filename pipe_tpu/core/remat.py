"""Activation-checkpoint (rematerialization) policy.

Capability parity with the reference checkpoint machinery — the
``Checkpointing``/``Checkpoint``/``Recompute`` autograd pair with RNG
save/restore and the phony-with-grad trick (reference ``pipeline.py:16,195-214,
256-260``; quoted module at ``README.md:450-537``; pptx slides 2–3) — collapsed
to its TPU-native essence: ``jax.checkpoint`` applied per micro-batch. The
entire runtime mechanism (deque handoff between Checkpoint.backward and
Recompute.backward, fork/join splicing, RNG state capture) disappears because

* recompute *ordering* is compiled: XLA places the rematerialized forward
  directly before its consuming backward ops;
* bit-identical dropout is free: the same explicit PRNG key is passed to the
  remat'd forward (reference needed ``save_rng_states``/``restore_rng_states``,
  ``README.md:528-537``);
* no phony tensors: ``jax.checkpoint`` differentiates fine with or without
  inputs that require gradients.

Three modes, same knob as reference ``pipe.py:255-260,354``:
``always`` / ``except_last`` / ``never`` → remat micro-batches
``[0, m)`` / ``[0, m-1)`` / ``[]``. Eval mode disables checkpointing entirely
(reference ``pipeline.py:153-155``). ``checkpoint_stop`` is computed against the
*actual* number of scattered micro-batches, fixing the non-divisible-chunks
off-by-one the reference README flags (``README.md:398``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

__all__ = [
    "CHECKPOINT_MODES",
    "checkpoint_stop",
    "apply_remat",
    "split_backward_stage",
    "SplitUnsupported",
]

CHECKPOINT_MODES = ("always", "except_last", "never")


def validate_mode(checkpoint: str) -> str:
    if checkpoint not in CHECKPOINT_MODES:
        raise ValueError(
            f"checkpoint is not one of {' | '.join(CHECKPOINT_MODES)!r}: "
            f"{checkpoint!r}")
    return checkpoint


def checkpoint_stop(checkpoint: str, num_microbatches: int, train: bool) -> int:
    """First micro-batch index NOT rematerialized.

    Reference map ``pipe.py:354`` (always → chunks, except_last → chunks-1,
    never → 0) evaluated against the realized micro-batch count, with the
    eval-mode off-switch of ``pipeline.py:153-155`` folded in.
    """
    validate_mode(checkpoint)
    if not train:
        return 0
    m = num_microbatches
    return {"always": m, "except_last": max(m - 1, 0), "never": 0}[checkpoint]


def apply_remat(fn: Callable, *, enabled: bool,
                policy=None) -> Callable:
    """Wrap a stage body in ``jax.checkpoint`` when enabled.

    ``policy`` optionally forwards a ``jax.checkpoint_policies`` member for
    selective remat (e.g. ``dots_saveable``) — a capability beyond the
    reference's all-or-nothing Checkpoint, kept because on TPU the
    FLOPs-vs-HBM tradeoff is the whole point of remat.
    """
    if not enabled:
        return fn
    if policy is not None:
        return jax.checkpoint(fn, policy=policy)
    return jax.checkpoint(fn)


# ---------------------------------------------------------------------------
# Generic structural B/W split (zero-bubble's real contract, derived).
#
# ``models/tp_lm.py`` hand-rolls the tapped/zs/wgrad triple for ONE block.
# :func:`split_backward_stage` derives the same triple from ANY stage fn by
# jaxpr surgery, so every model in the zoo gets a params-constant B vjp and a
# contraction-only W without writing a tapped forward by hand.
#
# The analysis classifies every jaxpr variable:
#
#   C  closure/shape constants (depend on nothing),
#   P  param-derived only (param leaves, casts/reshapes of them),
#   D  data-derived only (activations, ctx key),
#   X  mixed (downstream of a param*data contraction).
#
# The W REGION is the set of equations with >= 1 P-class input: the
# param-side prep chain (dtype casts, scales) plus every param*data mixing
# op (matmuls, layernorm scale/shift, embedding gathers). Region outputs
# that escape to the data side get a zero INJECTED at them (``h + 0`` is a
# no-op forward, but ``jax.vjp`` w.r.t. the zeros hands back exactly those
# outputs' cotangents — ``g_zs``); region-internal edges whose every
# consumer is also in the region CHAIN through the replay instead. The
# region's data-side inputs (post-injection where applicable) are the TAPS.
#
# W then is ``jax.linear_transpose`` of the region replay as a function of
# the param leaves with taps closed over as constants: nothing but the
# weight-grad contractions, and it needs only param AVALS, never values.
#
# Injected region outputs are CUT in the replay: a region eqn consuming one
# reads its tap (constant), not the recomputed producer value. This is what
# keeps grads exact when params feed cascaded ops (ln gamma -> ffn w1): the
# cotangent arriving at an injection point is already the FULL dL/dv (B ran
# the whole data-side chain, including through downstream region ops with
# params held constant), so letting the replay ALSO route it into the
# producer would double-count.
# ---------------------------------------------------------------------------


class SplitUnsupported(ValueError):
    """The stage fn's param usage cannot be auto-split (nonlinear in
    params inside the W region, params leaking into the stage output, or a
    forward that closes over traced values). The message says which; fall
    back to a hand-rolled ``SplitBackwardStage`` (see ``ops/tp_layers``)."""


def _ctx_arrays(ctx):
    """The StageCtx fields that are jax values (traced or concrete), as an
    explicit arg list, plus a rebuild closure and a static-fields cache key.
    StageCtx is deliberately NOT a pytree (static fields steer tracing), so
    the split threads its dynamic leaves by hand."""
    dyn_names, dyn_vals, static = [], [], []
    for f in dataclasses.fields(ctx):
        v = getattr(ctx, f.name)
        if isinstance(v, (jax.Array, jax.core.Tracer)):
            dyn_names.append(f.name)
            dyn_vals.append(v)
        else:
            static.append((f.name, v))

    def rebuild(vals):
        return dataclasses.replace(ctx, **dict(zip(dyn_names, vals)))

    return dyn_vals, rebuild, (tuple(dyn_names), tuple(static))


def _aval_sig(leaves):
    return tuple((tuple(l.shape), str(l.dtype)) for l in leaves)


class _SplitPlan:
    """One traced-and-classified stage body: everything tapped/zs/wgrad
    need, computed once per (arg avals, static ctx) signature."""

    def __init__(self, closed, n_param_leaves: int, params_treedef,
                 out_tree):
        jc = jax.core
        jaxpr = closed.jaxpr
        if any(isinstance(c, jc.Tracer) for c in closed.consts):
            raise SplitUnsupported(
                "stage fn closes over traced values (its jaxpr has tracer "
                "consts) — pass everything through params/h/ctx so the "
                "split's replay can be cached")
        self.closed = closed
        self.params_treedef = params_treedef
        self.out_tree = out_tree
        self.n_p = n_param_leaves

        cls: dict = {}
        for v in jaxpr.constvars:
            cls[v] = "C"
        for i, v in enumerate(jaxpr.invars):
            cls[v] = "P" if i < n_param_leaves else "D"
        consumers: dict = {}
        producer: dict = {}
        m_set = set()
        for k, eqn in enumerate(jaxpr.eqns):
            kinds = set()
            for a in eqn.invars:
                if isinstance(a, jc.Var):
                    kinds.add(cls[a])
                    consumers.setdefault(a, []).append(k)
            if "P" in kinds:
                m_set.add(k)
                out_cls = "P" if kinds <= {"P", "C"} else "X"
            elif "X" in kinds:
                out_cls = "X"
            elif "D" in kinds:
                out_cls = "D"
            else:
                out_cls = "C"
            for v in eqn.outvars:
                cls[v] = out_cls
                producer[v] = k
        self.cls = cls
        self._consumers = consumers
        self._producer = producer
        self._m_set = m_set

        outvar_set = {v for v in jaxpr.outvars if isinstance(v, jc.Var)}
        self._outvar_set = outvar_set
        for v in jaxpr.outvars:
            if isinstance(v, jc.Var) and cls[v] == "P":
                raise SplitUnsupported(
                    "stage fn returns a params-only value; its cotangent "
                    "would be dropped by the params-constant B pass")

        # Build with chaining first (fewest zs/taps), prove the transpose;
        # a probe failure WITHOUT a missing-transpose-rule proof usually
        # means a chained edge crossed a second param contraction (the
        # replay then multiplies two param-dependent values — jax's
        # bilinear transpose asserts). Injection is always gradient-exact
        # (chaining is only a zs/taps economy), so rebuild chain-free and
        # re-prove before giving up.
        self._build(allow_chain=True)
        err = self._probe_transpose()
        if err is not None:
            if self._nonlinear_proof(err) is not None:
                raise SplitUnsupported(
                    f"W region is not linear in the params (no "
                    f"transpose rule for an op on the param path: "
                    f"{self._nonlinear_proof(err)}); params may only pass "
                    f"through linear/structural ops before their first "
                    f"contraction with data — use a hand-rolled "
                    f"SplitBackwardStage for this stage fn") from err
            if self.chained:
                self._build(allow_chain=False)
                err = self._probe_transpose()
                if err is not None and \
                        self._nonlinear_proof(err) is not None:
                    raise SplitUnsupported(
                        f"W region is not linear in the params even with "
                        f"every region output injected "
                        f"({self._nonlinear_proof(err)}); use a "
                        f"hand-rolled SplitBackwardStage") from err
            # a residual inconclusive failure (pjit/custom_jvp bodies
            # that only transpose concretely) defers to wgrad()'s
            # runtime guard

    # chaining a param-dependent value into a consumer that combines it
    # with the param side is only linear when the combination is ADDITIVE
    # (ln: gamma*h -> +beta). A multiplicative consumer (dot, mul — the
    # attention q/k cascade) would square the param degree.
    _ADDITIVE = frozenset(["add", "add_any", "sub", "neg", "concatenate"])

    def _build(self, allow_chain: bool):
        """Pick inject-vs-chain for region outputs, prune the replay,
        collect taps. ``allow_chain=False`` injects EVERY inexact region
        output — more zs/taps, but the replay never recomputes a
        param-dependent value, so cascaded param contractions stay
        linear."""
        jc = jax.core
        jaxpr = self.closed.jaxpr
        cls, consumers = self.cls, self._consumers
        producer, m_set = self._producer, self._m_set

        # chain-vs-inject for the region's mixed outputs
        inject, chained = [], set()
        for k in sorted(m_set):
            for v in jaxpr.eqns[k].outvars:
                if cls[v] != "X":
                    continue
                cons = consumers.get(v, [])
                if allow_chain and cons \
                        and all(c in m_set for c in cons) \
                        and all(jaxpr.eqns[c].primitive.name
                                in self._ADDITIVE for c in cons) \
                        and v not in self._outvar_set:
                    chained.add(v)
                elif jnp.issubdtype(v.aval.dtype, jnp.inexact):
                    inject.append(v)
                # non-inexact mixed outputs carry no cotangent: cut silently
        self.inject = inject
        self.inject_set = set(inject)
        self.chained = chained

        # prune the replay to eqns actually reaching an injection point
        needed = set()
        stack = [producer[v] for v in inject]
        while stack:
            k = stack.pop()
            if k in needed:
                continue
            needed.add(k)
            for a in jaxpr.eqns[k].invars:
                if not isinstance(a, jc.Var) or a not in producer:
                    continue
                if a in self.inject_set:
                    continue  # cut: replay reads the tap, not the producer
                if cls[a] in ("C", "P") or a in chained:
                    stack.append(producer[a])
        self.replay_eqns = sorted(needed)

        # taps: data-side inputs of replayed region eqns
        tap_vars, tap_set = [], set()
        for k in self.replay_eqns:
            if k not in m_set:
                continue
            for a in jaxpr.eqns[k].invars:
                if (isinstance(a, jc.Var) and cls[a] in ("D", "X")
                        and a not in chained and a not in tap_set):
                    tap_set.add(a)
                    tap_vars.append(a)
        self.tap_vars = tap_vars
        self.param_structs = [
            jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
            for v in jaxpr.invars[:self.n_p]]
        self.zs_structs = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                           for v in inject]
        self.tap_structs = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                            for v in tap_vars]

    def _probe_transpose(self):
        """Prove the W region transposes NOW (abstractly), not at the
        first real W step: jax.linear_transpose only trips over a
        nonlinear param path (exp(w), w*w, ...) when the returned
        transpose is CALLED, so an eval_shape probe is the earliest
        honest check. Returns the exception on failure, None on proof."""
        if not self.inject:
            return None

        def _probe(gz, taps):
            t = jax.linear_transpose(
                lambda pl: self._replay(pl, taps), self.param_structs)
            return t(gz)

        try:
            jax.eval_shape(_probe, list(self.zs_structs),
                           list(self.tap_structs))
        except Exception as e:
            return e
        return None

    @staticmethod
    def _nonlinear_proof(err):
        """Walk the cause chain for a missing transpose rule — the only
        failure that PROVES a nonlinear param path. Other abstract-eval
        failures (bilinear asserts from chained edges, pjit quirks) are
        structural or inconclusive."""
        c = err
        while c is not None and not isinstance(c, NotImplementedError):
            c = c.__cause__
        return c

    # -- tapped forward: eval the whole jaxpr, adding zs at injection
    # points and recording taps. Mirrors jax.core.eval_jaxpr's bind loop so
    # pjit / custom_jvp_call / scan eqns run atomically and stay
    # differentiable (everything binds on the caller's tracers).
    def eval_tapped(self, args, zs):
        jc = jax.core
        jaxpr = self.closed.jaxpr
        if len(zs) != len(self.inject):
            raise ValueError(
                f"zs has {len(zs)} leaves but this stage traces to "
                f"{len(self.inject)} injection points — zs must come from "
                f"this split's zs_fn (is the forward's structure "
                f"ctx-dependent?)")
        env: dict = {}

        def read(a):
            return a.val if isinstance(a, jc.Literal) else env[a]

        for v, c in zip(jaxpr.constvars, self.closed.consts):
            env[v] = c
        for v, val in zip(jaxpr.invars, args):
            env[v] = val
        zmap = {v: z for v, z in zip(self.inject, zs)}
        for eqn in jaxpr.eqns:
            subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
            ans = eqn.primitive.bind(*subfuns, *map(read, eqn.invars),
                                     **bind_params)
            outs = ans if eqn.primitive.multiple_results else [ans]
            for v, val in zip(eqn.outvars, outs):
                if v in zmap:
                    val = val + zmap[v]
                env[v] = val
        out = [read(v) for v in jaxpr.outvars]
        taps = [env[v] for v in self.tap_vars]
        return out, taps

    # -- the W region replay: params -> pre-injection region outputs, with
    # taps as closure constants. Linear in params by construction (or the
    # transpose below fails loudly).
    def _replay(self, param_leaves, tap_vals):
        jc = jax.core
        jaxpr = self.closed.jaxpr
        env: dict = {}
        taps = dict(zip(self.tap_vars, tap_vals))

        def read(a):
            if isinstance(a, jc.Literal):
                return a.val
            if a in self.inject_set:
                return taps[a]  # cut edge: constant, post-injection value
            return env[a] if a in env else taps[a]

        for v, c in zip(jaxpr.constvars, self.closed.consts):
            env[v] = c
        for v, val in zip(jaxpr.invars[:self.n_p], param_leaves):
            env[v] = val
        for k in self.replay_eqns:
            eqn = jaxpr.eqns[k]
            subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
            ans = eqn.primitive.bind(*subfuns, *map(read, eqn.invars),
                                     **bind_params)
            outs = ans if eqn.primitive.multiple_results else [ans]
            for v, val in zip(eqn.outvars, outs):
                env[v] = val
        return [env[v] for v in self.inject]

    def wgrad(self, taps, gzs):
        def region(param_leaves):
            return self._replay(param_leaves, list(taps))

        try:
            transpose = jax.linear_transpose(region, self.param_structs)
            (gp_leaves,) = transpose(list(gzs))
        except Exception as e:  # pragma: no cover - plan-time probe
            # catches this first; kept for jax version drift
            raise SplitUnsupported(
                f"W region is not linear in the params "
                f"(jax.linear_transpose failed: {e}); use a hand-rolled "
                f"SplitBackwardStage for this stage fn") from e
        gp_leaves = [
            jnp.zeros(s.shape, s.dtype) if g is None else g
            for g, s in zip(gp_leaves, self.param_structs)]
        return jax.tree_util.tree_unflatten(self.params_treedef, gp_leaves)


def split_backward_stage(stage_fn: Callable, *,
                         canonical_key: Any = None):
    """Derive a ``SplitBackwardStage`` for ANY 3-arg stage fn.

    ``stage_fn(params_g, h, ctx) -> h_out`` is traced and classified per
    the module notes above; the returned object carries the protocol the
    scheduled executor's split path expects (``tapped_fn``/``wgrad_fn``/
    ``zs_fn``). ``ScheduledPipeline(split_stage="auto")`` calls this on its
    own ``stage_fn``.

    The analysis re-runs (and re-caches) per distinct (arg avals, static
    ctx fields) signature — microbatch shape changes or train/eval flips
    get their own plan. ``zs_fn(params_g, h)`` has no ctx, so it traces a
    CANONICAL one (train=True, a concrete PRNG key — the executor always
    feeds both); dropout and other key-consuming ops are data-side and
    cannot move the injection points, and ``tapped_fn`` cross-checks the
    zs structure against its own trace anyway.

    Limits (raise :class:`SplitUnsupported`): params must enter the
    forward LINEARLY up to the first param*data contraction (casts, scales
    fine; ``exp(w)`` not); the stage must not return a params-only value;
    stage fns whose zs sizing needs bound mesh axes (collectives inside)
    need a hand-rolled split. ``canonical_key`` overrides the zs_fn trace
    key (match the executor's key impl when tracing with typed keys).
    """
    plans: dict = {}

    def _plan(params_g, h, ctx):
        p_leaves, p_def = jax.tree_util.tree_flatten(params_g)
        h_leaves, h_def = jax.tree_util.tree_flatten(h)
        cvals, rebuild, static_sig = _ctx_arrays(ctx)
        sig = (_aval_sig(p_leaves + h_leaves + cvals), p_def, h_def,
               static_sig)
        plan = plans.get(sig)
        if plan is None:
            def wrapper(pl, hl, cl):
                p = jax.tree_util.tree_unflatten(p_def, pl)
                hh = jax.tree_util.tree_unflatten(h_def, hl)
                return stage_fn(p, hh, rebuild(cl))

            closed, out_shape = jax.make_jaxpr(wrapper, return_shape=True)(
                p_leaves, h_leaves, cvals)
            out_tree = jax.tree_util.tree_structure(out_shape)
            plan = _SplitPlan(closed, len(p_leaves), p_def, out_tree)
            plans[sig] = plan
            # wgrad sees only (taps, gzs): index the plan by their avals
            # too. A collision can only come from a same-shape retrace
            # (e.g. train/eval), whose W region is identical — last wins.
            plans[("w", _aval_sig(plan.tap_structs),
                   _aval_sig(plan.zs_structs))] = plan
        return plan, p_leaves + h_leaves + cvals

    def tapped_fn(params_g, h, ctx, zs):
        plan, args = _plan(params_g, h, ctx)
        zl = list(zs)
        out, taps = plan.eval_tapped(args, zl)
        return jax.tree_util.tree_unflatten(plan.out_tree, out), taps

    def wgrad_fn(taps, gzs):
        tl, gl = list(taps), list(gzs)
        plan = plans.get(("w", _aval_sig(tl), _aval_sig(gl)))
        if plan is None:
            raise ValueError(
                "wgrad_fn called before tapped_fn traced this stage "
                "signature — taps/gzs do not come from this split")
        return plan.wgrad(tl, gl)

    def zs_fn(params_g, h):
        from .partition import StageCtx
        key = canonical_key
        if key is None:
            from ..utils.rng import make_key
            key = make_key(0)
        plan, _ = _plan(params_g, h,
                        StageCtx(key=key, train=True, stage=0))
        return [jnp.zeros(s.shape, s.dtype) for s in plan.zs_structs]

    from ..parallel.scheduled import SplitBackwardStage
    return SplitBackwardStage(tapped_fn=tapped_fn, wgrad_fn=wgrad_fn,
                              zs_fn=zs_fn)
