"""Activation-checkpoint (rematerialization) policy.

Capability parity with the reference checkpoint machinery — the
``Checkpointing``/``Checkpoint``/``Recompute`` autograd pair with RNG
save/restore and the phony-with-grad trick (reference ``pipeline.py:16,195-214,
256-260``; quoted module at ``README.md:450-537``; pptx slides 2–3) — collapsed
to its TPU-native essence: ``jax.checkpoint`` applied per micro-batch. The
entire runtime mechanism (deque handoff between Checkpoint.backward and
Recompute.backward, fork/join splicing, RNG state capture) disappears because

* recompute *ordering* is compiled: XLA places the rematerialized forward
  directly before its consuming backward ops;
* bit-identical dropout is free: the same explicit PRNG key is passed to the
  remat'd forward (reference needed ``save_rng_states``/``restore_rng_states``,
  ``README.md:528-537``);
* no phony tensors: ``jax.checkpoint`` differentiates fine with or without
  inputs that require gradients.

Three modes, same knob as reference ``pipe.py:255-260,354``:
``always`` / ``except_last`` / ``never`` → remat micro-batches
``[0, m)`` / ``[0, m-1)`` / ``[]``. Eval mode disables checkpointing entirely
(reference ``pipeline.py:153-155``). ``checkpoint_stop`` is computed against the
*actual* number of scattered micro-batches, fixing the non-divisible-chunks
off-by-one the reference README flags (``README.md:398``).
"""

from __future__ import annotations

from typing import Callable

import jax

__all__ = [
    "CHECKPOINT_MODES",
    "checkpoint_stop",
    "apply_remat",
]

CHECKPOINT_MODES = ("always", "except_last", "never")


def validate_mode(checkpoint: str) -> str:
    if checkpoint not in CHECKPOINT_MODES:
        raise ValueError(
            f"checkpoint is not one of {' | '.join(CHECKPOINT_MODES)!r}: "
            f"{checkpoint!r}")
    return checkpoint


def checkpoint_stop(checkpoint: str, num_microbatches: int, train: bool) -> int:
    """First micro-batch index NOT rematerialized.

    Reference map ``pipe.py:354`` (always → chunks, except_last → chunks-1,
    never → 0) evaluated against the realized micro-batch count, with the
    eval-mode off-switch of ``pipeline.py:153-155`` folded in.
    """
    validate_mode(checkpoint)
    if not train:
        return 0
    m = num_microbatches
    return {"always": m, "except_last": max(m - 1, 0), "never": 0}[checkpoint]


def apply_remat(fn: Callable, *, enabled: bool,
                policy=None) -> Callable:
    """Wrap a stage body in ``jax.checkpoint`` when enabled.

    ``policy`` optionally forwards a ``jax.checkpoint_policies`` member for
    selective remat (e.g. ``dots_saveable``) — a capability beyond the
    reference's all-or-nothing Checkpoint, kept because on TPU the
    FLOPs-vs-HBM tradeoff is the whole point of remat.
    """
    if not enabled:
        return fn
    if policy is not None:
        return jax.checkpoint(fn, policy=policy)
    return jax.checkpoint(fn)
