"""Profile-driven auto-planner: balance, schedule, and microbatching from
measured costs.

The reference ``Pipe`` makes the user hand-pick ``balance``, ``chunks``
and checkpointing; pipe_tpu inherited that. This module closes the loop
with the machinery five prior PRs built:

1. **Calibrate** (:func:`profile_model` / :func:`profile_from_calibration`):
   a handful of real measured steps fold per-layer forward/backward costs
   (``core/balance.py:profile_times`` — median-of-k, warmup-discarded) and
   per-layer activation/parameter sizes into a :class:`CostProfile`. A
   serialized step-time calibration (``obs/zb_model.py:calibrate``) supplies
   the split-overhead ``sigma`` and the per-cycle machinery overhead ``o``
   — and its fit residual: a profile built on a calibration whose relative
   residual exceeds :data:`MAX_REL_RESIDUAL` is REFUSED with a loud
   warning (:class:`CalibrationError`), because every ranking downstream
   would inherit a falsified cost model.

2. **Search** (:func:`search`): enumerate (stage cut points × schedule
   family {gpipe, 1f1b, interleaved, zb-h1/h2, bring-your-own
   ``Schedule``} × micro-batch count m × interleave v × split_stage).
   Every candidate's op table must PROVE itself — ``verify_op_tables`` /
   ``verify_interleaved_op_tables`` plus a ``compile_phases`` verdict —
   before it is scored: predicted step time from the heterogeneous
   generalization of ``obs/zb_model.py:schedule_wall``
   (:func:`predict_wall`, per-stage cost columns instead of one scalar
   ``f``), predicted peak memory from the executor-shared
   ``core/memplan.py:estimate_memory`` formula, pruned against a
   user-supplied cap.

3. **Plan** (:class:`Plan`): a JSON-serializable artifact — chosen config,
   predicted step time, predicted peak memory, ranked runners-up — that
   ``Pipe(plan=...)`` and ``Trainer(plan="auto")`` consume directly, and
   ``tools/plan_bench.py`` validates against measured step times
   (``PLAN_r12.json``).

Grounding: "Efficient Pipeline Planning for Expedited Distributed DNN
Training" and "A Flexible Programmable Pipeline Parallelism Framework"
(PAPERS.md) — profile a few calibration steps, then search the plan space
under a cost model instead of asking the user.

Determinism: the search is a pure function of the profile and its keyword
knobs — no RNG, no clock reads — and ties break on the lexicographic
(schedule name, m, v, split) key, so a fixed profile always yields the
same ranked plan list (pinned in ``tests/test_planner.py``).
"""

from __future__ import annotations

import dataclasses
import json
import warnings
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from ..obs.zb_model import OpCosts, fitted_op_costs
from .balance import _bottleneck_split, _layer_specs, stage_costs
from .memplan import MemoryPlanInputs, estimate_memory
from .partition import BalanceError, split_balance
from .schedule import (BWD, FWD, WGRAD, Schedule, compile_phases,
                       get_schedule, verify_interleaved_op_tables,
                       verify_op_tables)

__all__ = ["CostProfile", "Plan", "CalibrationError", "MAX_REL_RESIDUAL",
           "profile_model", "profile_from_calibration", "uniform_profile",
           "predict_wall", "search", "auto_plan", "spec_speedup",
           "spec_breakeven_acceptance"]

# Refuse to rank on a calibration whose relative fit residual exceeds
# this: a quarter of the signal unexplained means the linear cost model
# (op counts x per-op costs + cycles x overhead) is the wrong model for
# the machine, and ranking schedules on it would be astrology. The
# committed cpu8 calibrations sit well below (ZB_CROSSOVER_r05: <= 0.06).
MAX_REL_RESIDUAL = 0.25

# Committed-calibration defaults for profiles built without a fresh fit
# (ZB_CROSSOVER_r05.json, structural split): sigma <= 1.41 across widths.
# The legacy stored-vjp split measured 1.90-2.33 (r04) — ~1.45x worse —
# which is how split_stage=False zb candidates are priced when the
# profile carries no legacy sigma of its own.
DEFAULT_SIGMA = 1.41
LEGACY_SIGMA_RATIO = 1.45


class CalibrationError(ValueError):
    """The cost-model calibration is not trustworthy enough to rank on."""


# ---------------------------------------------------------------------------
# CostProfile: what calibration measures, what the search consumes
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CostProfile:
    """Measured per-layer costs + sizes, and the machine model constants.

    ``layer_fwd_s``/``layer_bwd_s`` are seconds per layer for one
    micro-batch of ``rows`` rows (bwd is the BACKWARD-only part; a fused
    BWD op costs ``bwd``, a split B or W op costs ``sigma * bwd / 2``).
    ``layer_act_bytes`` is each layer's OUTPUT activation size at ``rows``
    rows — boundary traffic and stash slots are priced off it.
    Costs and activation bytes scale linearly with rows-per-micro-batch
    when the search trades m against micro-batch size.
    """

    layer_fwd_s: Tuple[float, ...]
    layer_bwd_s: Tuple[float, ...]
    layer_param_bytes: Tuple[int, ...]
    layer_act_bytes: Tuple[int, ...]
    rows: int = 1
    sigma: float = DEFAULT_SIGMA        # split-backward overhead factor
    sigma_fused_split: Optional[float] = None   # legacy stored-vjp sigma
    o: float = 0.0                      # per-cycle machinery overhead, s
    mode: str = "serialized"            # serialized (cpu8) | parallel
    rel_residual: float = 0.0           # of the calibration behind sigma/o
    source: str = "unspecified"

    def __post_init__(self):
        n = len(self.layer_fwd_s)
        for f_ in ("layer_bwd_s", "layer_param_bytes", "layer_act_bytes"):
            if len(getattr(self, f_)) != n:
                raise ValueError(f"{f_} covers {len(getattr(self, f_))} "
                                 f"layers, layer_fwd_s covers {n}")
        if self.mode not in ("serialized", "parallel"):
            raise ValueError(f"mode must be serialized|parallel, "
                             f"got {self.mode!r}")
        if self.rel_residual > MAX_REL_RESIDUAL:
            warnings.warn(
                f"REFUSING to plan on this calibration: relative fit "
                f"residual {self.rel_residual:.3f} exceeds "
                f"{MAX_REL_RESIDUAL} — the linear cost model does not "
                f"describe this machine, so any schedule ranking built on "
                f"it would be noise. Re-measure (more iters, quieter "
                f"host), or pass analytic costs explicitly.", stacklevel=3)
            raise CalibrationError(
                f"calibration rel_residual {self.rel_residual:.3f} > "
                f"{MAX_REL_RESIDUAL}")

    @property
    def n_layers(self) -> int:
        return len(self.layer_fwd_s)

    @property
    def sigma_legacy(self) -> float:
        return (self.sigma_fused_split if self.sigma_fused_split is not None
                else self.sigma * LEGACY_SIGMA_RATIO)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "CostProfile":
        d = json.loads(text)
        for k in ("layer_fwd_s", "layer_bwd_s", "layer_param_bytes",
                  "layer_act_bytes"):
            d[k] = tuple(d[k])
        return cls(**d)


def profile_model(module, params, sample, *, repeat: int = 5,
                  warmup: int = 1, key=None, sigma: float = DEFAULT_SIGMA,
                  o: float = 0.0, mode: str = "serialized",
                  rel_residual: float = 0.0) -> CostProfile:
    """The calibration pass over a real model: run each layer for a
    handful of real (jitted, host-synced) steps and fold the measured
    forward/backward costs plus parameter/activation sizes into a
    :class:`CostProfile`. ``sample`` must be ONE micro-batch of the rows
    the pipeline will see (the search scales costs linearly in rows when
    it trades m against micro-batch size).

    ``sigma``/``o``/``rel_residual`` come from a step-time calibration
    when one exists (:func:`obs.zb_model.calibrate` →
    :func:`profile_from_calibration` merges them); the defaults are the
    committed cpu8 fit.
    """
    import jax
    import jax.numpy as jnp
    from .balance import profile_times

    fwd = profile_times(module, params, sample, backward=False,
                        repeat=repeat, warmup=warmup, key=key)
    tot = profile_times(module, params, sample, backward=True,
                        repeat=repeat, warmup=warmup, key=key)
    # profile_times(backward=True) measures fwd+bwd together; the
    # backward-only part clamps at one forward below (timer noise can
    # push tot under fwd for tiny layers; a backward cheaper than the
    # forward it differentiates is not physical for matmul chains).
    bwd = [max(t - f, f) for f, t in zip(fwd, tot)]
    specs = _layer_specs(module, params, sample)
    p_bytes, a_bytes = [], []
    for layer, p, spec in zip(module, params, specs):
        p_bytes.append(int(sum(
            a.size * a.dtype.itemsize for a in jax.tree_util.tree_leaves(p)
            if hasattr(a, "dtype"))))
        out = layer.out_spec(p, spec)
        outs = out if isinstance(out, (tuple, list)) else [out]
        a_bytes.append(int(sum(
            int(np.prod(o_.shape)) * o_.dtype.itemsize for o_ in outs)))
    rows = int(jnp.shape(sample)[0]) if jnp.ndim(sample) else 1
    return CostProfile(
        layer_fwd_s=tuple(fwd), layer_bwd_s=tuple(bwd),
        layer_param_bytes=tuple(p_bytes), layer_act_bytes=tuple(a_bytes),
        rows=rows, sigma=sigma, o=o, mode=mode, rel_residual=rel_residual,
        source="profile_model")


def profile_from_calibration(calib: dict, *, n_layers: int, rows: int,
                             layer_param_bytes: Union[int, Sequence[int]] = 0,
                             layer_act_bytes: Union[int, Sequence[int]] = 0,
                             width: Optional[int] = None,
                             mode: str = "serialized") -> CostProfile:
    """A :class:`CostProfile` from a measured step-time calibration
    (:func:`obs.zb_model.calibrate` over real 1f1b/zb steps). The fit's
    per-STAGE forward cost ``f`` (at ``calib['n']`` stages) spreads
    uniformly over ``n_layers`` layers — exact for homogeneous stacks
    (the transformer zoo), which is the only thing a step-level fit can
    resolve anyway. Refuses (loudly) when the fit's relative residual
    exceeds :data:`MAX_REL_RESIDUAL` — see :class:`CalibrationError`."""
    costs: OpCosts = fitted_op_costs(calib, width)
    rr = float(calib.get("rel_residual",
                         max(calib["rel_residual_per_width"])))
    stages_at_fit = int(calib["n"])
    layer_f = costs.f * stages_at_fit / n_layers
    if isinstance(layer_param_bytes, int):
        layer_param_bytes = (layer_param_bytes,) * n_layers
    if isinstance(layer_act_bytes, int):
        layer_act_bytes = (layer_act_bytes,) * n_layers
    return CostProfile(
        layer_fwd_s=(layer_f,) * n_layers,
        layer_bwd_s=(2.0 * layer_f,) * n_layers,
        layer_param_bytes=tuple(int(b) for b in layer_param_bytes),
        layer_act_bytes=tuple(int(b) for b in layer_act_bytes),
        rows=rows, sigma=costs.sigma, o=max(costs.o, 0.0), mode=mode,
        rel_residual=rr, source="zb_model.calibrate")


def uniform_profile(n_layers: int, *, rows: int = 1, f: float = 1.0,
                    sigma: float = DEFAULT_SIGMA, o_over_f: float = 0.1,
                    layer_param_bytes: int = 0, layer_act_bytes: int = 0,
                    mode: str = "parallel") -> CostProfile:
    """Analytic fallback profile: uniform unit-cost layers, committed
    sigma, overhead as a fraction of ``f``. This is what
    ``Trainer(plan='auto')`` ranks on when no measured profile is given —
    correct RELATIVE costs for homogeneous stage bodies (PipelinedLM),
    which is all the argmin needs."""
    return CostProfile(
        layer_fwd_s=(f,) * n_layers, layer_bwd_s=(2.0 * f,) * n_layers,
        layer_param_bytes=(layer_param_bytes,) * n_layers,
        layer_act_bytes=(layer_act_bytes,) * n_layers,
        rows=rows, sigma=sigma, o=o_over_f * f, mode=mode,
        source="uniform")


# ---------------------------------------------------------------------------
# Heterogeneous wall model: per-stage cost columns under an op table
# ---------------------------------------------------------------------------


def predict_wall(op: np.ndarray, grp: Optional[np.ndarray],
                 stage_fwd_s: Sequence[float],
                 stage_bwd_s: Sequence[float], *, d: int, sigma: float,
                 o: float, mode: str, recompute: bool = False) -> float:
    """Predicted wall seconds of one step — the heterogeneous
    generalization of :func:`obs.zb_model.schedule_wall`: instead of one
    scalar ``f`` for every stage, each of the ``S = v*d`` virtual stages
    brings its own forward/backward cost (the per-stage vectors
    :func:`core.balance.stage_costs` produces for a candidate cut).
    Virtual stage ``s = grp[t, p] * d + p`` prices the op at ``(t, p)``:

    * ``FWD`` = ``f_s``; fused ``BWD`` = ``b_s`` (+ ``f_s`` recompute tax
      under non-'never' checkpointing);
    * split tables: B and W each ``sigma * b_s / 2`` — the same pricing
      :class:`obs.zb_model.OpCosts` uses, so with uniform cost columns
      and ``b = 2f`` this function equals ``schedule_wall`` exactly
      (pinned in ``tests/test_planner.py``).
    """
    op = np.asarray(op)
    T, cols = op.shape
    if cols != d:
        raise ValueError(f"op table has {cols} device columns, d={d}")
    S = len(stage_fwd_s)
    if len(stage_bwd_s) != S or S % d:
        raise ValueError(f"stage cost vectors must cover v*d stages "
                         f"(got {S} and {len(stage_bwd_s)} for d={d})")
    grp = (np.zeros_like(op) if grp is None else np.asarray(grp))
    s_at = grp * d + np.arange(d)[None, :]
    f_at = np.asarray(stage_fwd_s, np.float64)[s_at]
    b_at = np.asarray(stage_bwd_s, np.float64)[s_at]
    split_table = bool((op == WGRAD).any())
    ct = np.zeros(op.shape, np.float64)
    ct[op == FWD] = f_at[op == FWD]
    if split_table:
        ct[op == BWD] = (sigma / 2.0) * b_at[op == BWD]
        ct[op == WGRAD] = (sigma / 2.0) * b_at[op == WGRAD]
    else:
        bb = b_at + (f_at if recompute else 0.0)
        ct[op == BWD] = bb[op == BWD]
    if mode == "parallel":
        return float(ct.max(axis=1).sum() + T * o)
    if mode == "serialized":
        return float(ct.sum() + T * o)
    raise ValueError(f"mode must be parallel|serialized, got {mode!r}")


# ---------------------------------------------------------------------------
# The Plan artifact
# ---------------------------------------------------------------------------

PLAN_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Plan:
    """One verified, scored pipeline configuration — the planner's unit of
    output and the front doors' unit of input (``Pipe(plan=...)``,
    ``Trainer(plan=...)``). JSON-serializable; ``runners_up`` carries the
    ranked alternatives' summaries so a human (or ``tools/plan_bench.py``)
    can see what the winner beat and by how much."""

    schedule: str
    m: int
    v: int
    balance: Tuple[int, ...]
    split_stage: bool
    checkpoint: str
    n_devices: int
    mode: str
    predicted_step_s: float
    predicted_s_per_row: float
    predicted_peak_bytes: int
    phase_ok: bool
    profile_source: str = "unspecified"
    runners_up: Tuple[dict, ...] = ()
    # Bring-your-own-schedule plans carry the live object (not JSON-round-
    # trippable; reloading such a plan requires re-supplying the object).
    schedule_ref: Optional[Schedule] = dataclasses.field(
        default=None, compare=False, repr=False)

    def summary(self) -> dict:
        return {"schedule": self.schedule, "m": self.m, "v": self.v,
                "balance": list(self.balance),
                "split_stage": self.split_stage,
                "checkpoint": self.checkpoint,
                "predicted_step_s": self.predicted_step_s,
                "predicted_s_per_row": self.predicted_s_per_row,
                "predicted_peak_bytes": self.predicted_peak_bytes,
                "phase_ok": self.phase_ok}

    def schedule_obj(self) -> Schedule:
        """The live Schedule this plan prescribes."""
        if self.schedule_ref is not None:
            return self.schedule_ref
        if self.schedule == "interleaved-1f1b":
            return get_schedule("interleaved-1f1b", interleave=self.v)
        return get_schedule(self.schedule)

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d.pop("schedule_ref")
        d["balance"] = list(self.balance)
        d["runners_up"] = list(self.runners_up)
        d["version"] = PLAN_VERSION
        return json.dumps(d, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Plan":
        d = json.loads(text)
        ver = d.pop("version", PLAN_VERSION)
        if ver != PLAN_VERSION:
            raise ValueError(f"plan version {ver} != {PLAN_VERSION}")
        d["balance"] = tuple(d["balance"])
        d["runners_up"] = tuple(d.get("runners_up", ()))
        return cls(**d)

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write(self.to_json() + "\n")

    @classmethod
    def load(cls, path: str) -> "Plan":
        with open(path) as fh:
            return cls.from_json(fh.read())


# ---------------------------------------------------------------------------
# The search
# ---------------------------------------------------------------------------


def _balance_candidates(profile: CostProfile, n_stages: int,
                        uniform_only: bool) -> List[Tuple[int, ...]]:
    """Candidate stage cut points for one stage count: the uniform
    ceil-split, the bottleneck-optimal cut by measured time, and the
    bottleneck-optimal cut by bytes (deduped, deterministic order)."""
    L = profile.n_layers
    if n_stages > L:
        return []
    out: List[Tuple[int, ...]] = []
    if uniform_only:
        if L % n_stages:
            return []
        return [tuple(split_balance(L, n_stages))]
    for costs in (None,
                  [f + b for f, b in zip(profile.layer_fwd_s,
                                         profile.layer_bwd_s)],
                  [p + a for p, a in zip(profile.layer_param_bytes,
                                         profile.layer_act_bytes)]):
        try:
            cut = (tuple(split_balance(L, n_stages)) if costs is None
                   else tuple(_bottleneck_split(costs, n_stages)))
        except BalanceError:
            continue
        if sum(costs or [0]) == 0 and costs is not None:
            continue    # size profile absent: the cut is meaningless
        if cut not in out:
            out.append(cut)
    return out


def _schedule_candidates(spec, v_options: Sequence[int]):
    """Expand one schedule spec into (name, v, Schedule, is_custom)."""
    if isinstance(spec, Schedule):
        return [(spec.name, spec.v, spec, True)]
    name = {"interleaved": "interleaved-1f1b"}.get(spec, spec)
    if name == "interleaved-1f1b":
        return [(name, v, get_schedule(name, interleave=v), False)
                for v in v_options if v > 1]
    return [(name, 1, get_schedule(name), False)]


def _device_param_bytes(balance: Sequence[int], profile: CostProfile,
                        d: int) -> int:
    """Max per-device parameter bytes: virtual stage s lives on device
    s % d (device-major interleaving)."""
    per_stage = stage_costs(balance, profile.layer_param_bytes)
    dev = [0.0] * d
    for s, b in enumerate(per_stage):
        dev[s % d] += b
    return int(max(dev))


def search(profile: CostProfile, *, n_devices: int,
           m_candidates: Sequence[int],
           batch_rows: Optional[int] = None,
           schedules: Sequence[Union[str, Schedule]] = (
               "gpipe", "1f1b", "interleaved-1f1b", "zb-h1", "zb-h2"),
           interleave_candidates: Sequence[int] = (2,),
           checkpoint: str = "never",
           memory_cap_bytes: Optional[int] = None,
           uniform_only: bool = False,
           phase_gate: bool = True,
           max_plans: int = 8) -> List[Plan]:
    """Rank the plan space under the profile's cost model.

    For each (schedule family × interleave v × m × stage cut ×
    split_stage) candidate: emit the op table, PROVE it
    (``verify_op_tables`` / the interleaved verifier; construction or
    verification failure prunes silently — an invalid table is not a
    plan), phase-compile it (``compile_phases``; with ``phase_gate`` a
    rejected table is pruned too, so every emitted plan lowers to the
    switch-free executor when ``d > 1``), price it
    (:func:`predict_wall` + :func:`core.memplan.estimate_memory`), and
    drop it if it busts ``memory_cap_bytes``.

    ``batch_rows`` fixes the global batch: rows-per-micro-batch becomes
    ``batch_rows / m`` (non-dividing m are skipped) and costs scale
    linearly from the profile's measured rows — this is the m-vs-
    micro-batch-size tradeoff. Without it, each m keeps the profile's
    rows per micro-batch and ranking is per-ROW throughput either way
    (``predicted_s_per_row``), so small-m and large-m candidates stay
    comparable.

    Returns plans best-first; ``plans[0].runners_up`` summarizes the
    rest. Deterministic for a fixed profile (no RNG, stable tiebreak).
    """
    if not m_candidates:
        raise ValueError("m_candidates must be non-empty")
    d = int(n_devices)
    plans: List[Plan] = []
    for spec in schedules:
        for name, v, sched, is_custom in _schedule_candidates(
                spec, interleave_candidates):
            S = v * d
            split_opts = ([True, False] if sched.splits_backward
                          and checkpoint == "never" else [False])
            for m in sorted(set(int(m) for m in m_candidates)):
                if batch_rows is not None:
                    if batch_rows % m:
                        continue
                    rows_mb = batch_rows // m
                else:
                    rows_mb = profile.rows
                scale = rows_mb / profile.rows
                for balance in _balance_candidates(profile, S,
                                                   uniform_only):
                    try:
                        tables = sched.op_tables(m, d if v > 1 else S)
                    except Exception:
                        continue        # constructor refused this (m, n)
                    op, mbi = tables[0], tables[1]
                    grp = tables[2] if len(tables) > 2 else None
                    try:
                        if v > 1:
                            verify_interleaved_op_tables(
                                op, mbi, grp, m, d, v)
                        else:
                            verify_op_tables(
                                op, mbi, m, S,
                                stash_slots=sched.stash_slots(m, S),
                                wstash_slots=(
                                    sched.wstash_slots(m, S)
                                    if sched.splits_backward else None))
                    except AssertionError:
                        continue        # table failed its proof: not a plan
                    verdict = compile_phases(op, mbi, grp, m=m, d=d, v=v)
                    if phase_gate and d > 1 and not verdict.accepted:
                        continue
                    f_vec = [scale * c for c in stage_costs(
                        balance, profile.layer_fwd_s)]
                    b_vec = [scale * c for c in stage_costs(
                        balance, profile.layer_bwd_s)]
                    for split in split_opts:
                        sigma = (profile.sigma if split
                                 else profile.sigma_legacy)
                        wall = predict_wall(
                            op, grp, f_vec, b_vec, d=d, sigma=sigma,
                            o=profile.o, mode=profile.mode,
                            recompute=checkpoint != "never")
                        act = int(np.ceil(scale * max(
                            profile.layer_act_bytes, default=0)))
                        mem = estimate_memory(
                            MemoryPlanInputs(
                                v=v,
                                stash_slots=sched.stash_slots(
                                    m, d if v > 1 else S),
                                wstash_slots=(
                                    sched.wstash_slots(m, S)
                                    if sched.splits_backward else 0),
                                checkpoint=checkpoint,
                                split_stage=split),
                            act_bytes=act,
                            param_bytes=_device_param_bytes(
                                balance, profile, d))
                        if memory_cap_bytes is not None \
                                and mem > memory_cap_bytes:
                            continue
                        plans.append(Plan(
                            schedule=name, m=m, v=v, balance=balance,
                            split_stage=split, checkpoint=checkpoint,
                            n_devices=d, mode=profile.mode,
                            predicted_step_s=wall,
                            predicted_s_per_row=wall / (m * rows_mb),
                            predicted_peak_bytes=mem,
                            phase_ok=bool(verdict.accepted),
                            profile_source=profile.source,
                            schedule_ref=sched if is_custom else None))
    plans.sort(key=lambda p: (p.predicted_s_per_row, p.schedule, p.m,
                              p.v, not p.split_stage, p.balance))
    plans = plans[:max_plans]
    if plans:
        tail = tuple(p.summary() for p in plans[1:])
        plans[0] = dataclasses.replace(plans[0], runners_up=tail)
    return plans


def auto_plan(module, params, sample, *, n_devices: int,
              m_candidates: Sequence[int], **search_kw) -> Plan:
    """Calibrate → search → best plan, in one call: profile the model's
    layers with real measured steps (:func:`profile_model`) and hand the
    ranked winner back. Raises :class:`BalanceError`-family errors only
    when NO candidate survives the proofs and the memory cap."""
    profile = profile_model(module, params, sample)
    plans = search(profile, n_devices=n_devices,
                   m_candidates=m_candidates, **search_kw)
    if not plans:
        raise BalanceError(
            "the planner found no feasible plan: every candidate failed "
            "table verification, phase compilation, or the memory cap")
    return plans[0]


# ---------------------------------------------------------------------------
# speculative-decode cost model: acceptance x draft cost as a plan input
# ---------------------------------------------------------------------------
#
# The serving profile's analog of predict_wall: should a deployment turn
# the spec lane on, and at which draft? Inputs are the two numbers the
# obs plane ships per deployment — ``serve.spec.acceptance_rate`` (the
# measured per-drafted-position acceptance) and
# ``serve.spec.draft_cost_frac`` (the drafter's work-unit share of a
# round, ``inference/draft.py:DraftSource.draft_cost_frac``) — plus one
# machine fact: how much a K-row teacher-forced verify chunk costs
# relative to a 1-row decode step (``chunk_cost_ratio``, ~1 on
# overhead/memory-bound decode, -> K on pure-FLOP-bound decode; the
# serve bench measures it as spec-off s_per_tok vs the chunk wall).


def spec_speedup(acceptance: float, draft_cost_frac: float, K: int,
                 chunk_cost_ratio: float = 1.0) -> float:
    """Predicted spec-on tokens/s over spec-off tokens/s.

    Per round the lane emits ``1 + acceptance*(K-1)`` tokens (the
    accepted draft prefix plus the correction) and pays
    ``chunk_cost_ratio`` single-step walls of verify plus the draft
    overhead — ``draft_cost_frac = d/(d+v)`` gives the draft/verify
    wall ratio ``f/(1-f)``, so a round costs ``chunk_cost_ratio/(1-f)``
    single steps. The spec-off baseline is 1 token per single step."""
    if K < 2:
        raise ValueError(f"spec needs K >= 2, got {K}")
    if not 0.0 <= acceptance <= 1.0:
        raise ValueError(f"acceptance must be in [0, 1], got {acceptance}")
    if not 0.0 <= draft_cost_frac < 1.0:
        raise ValueError(
            f"draft_cost_frac must be in [0, 1), got {draft_cost_frac}")
    if chunk_cost_ratio <= 0.0:
        raise ValueError(
            f"chunk_cost_ratio must be > 0, got {chunk_cost_ratio}")
    emitted = 1.0 + acceptance * (K - 1)
    round_cost = chunk_cost_ratio / (1.0 - draft_cost_frac)
    return emitted / round_cost


def spec_breakeven_acceptance(draft_cost_frac: float, K: int,
                              chunk_cost_ratio: float = 1.0) -> float:
    """The acceptance rate at which :func:`spec_speedup` crosses 1.0 —
    below it the lane is a slowdown and the plan should keep spec off.
    Returns a value clipped to [0, 1]; 1.0 means the draft can never
    pay for itself at this K (e.g. FLOP-bound verify with an expensive
    draft), 0.0 means any acceptance wins (free draft, free chunk)."""
    if K < 2:
        raise ValueError(f"spec needs K >= 2, got {K}")
    if not 0.0 <= draft_cost_frac < 1.0:
        raise ValueError(
            f"draft_cost_frac must be in [0, 1), got {draft_cost_frac}")
    if chunk_cost_ratio <= 0.0:
        raise ValueError(
            f"chunk_cost_ratio must be > 0, got {chunk_cost_ratio}")
    a = (chunk_cost_ratio / (1.0 - draft_cost_frac) - 1.0) / (K - 1)
    return float(min(max(a, 0.0), 1.0))
