"""Stage partitioning and fail-fast validation.

Capability parity with the reference partitioner (``_split_module``,
``_retrieve_device``, ``_assemble_partition``, ``_verify_module``,
``_verify_splitting`` — reference ``pipe.py:61-87,94-118,181-218``), re-idiomized:
on TPU there are no per-module device tags to cut partitions at, so stage
placement is explicit — a stage count plus an optional ``balance`` list (the
ceil-split default mirrors the tutorial's ``nn.Sequential`` split,
``main.py:139-140``) — and device inference is replaced by mesh sharding at the
executor layer.
"""

from __future__ import annotations

import dataclasses
import inspect
import math
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax

__all__ = [
    "BalanceError",
    "StageCtx",
    "Stage",
    "verify_stages",
    "verify_splitting",
    "split_balance",
    "partition_sequence",
]


class BalanceError(ValueError):
    """Raised when layers cannot be split into the requested stages.

    Name kept for API parity with reference ``BalanceError`` (``pipe.py:36-39``).
    The reference's ``_recommend_auto_balance`` advertises a ``balance_by_time``
    that was never shipped (``pipe.py:42-58``); here :func:`split_balance` is the
    real, shipped equivalent (uniform by default, cost-weighted optional).
    """


@dataclasses.dataclass(frozen=True)
class StageCtx:
    """Per-invocation context threaded to stage bodies.

    Replaces the reference's implicit runtime state: the RNG fork/restore of the
    checkpointing layer (``README.md:528-537``) becomes an explicit ``key``
    (bit-identical dropout under recompute is free by construction — the same
    key is simply passed again), and (microbatch, stage) indices feed profiler
    scope names (the ``chunk%d-part%d`` spans of ``pipeline.py:205-210``).
    """

    key: Optional[jax.Array] = None
    train: bool = False
    microbatch: int = 0
    stage: int = 0
    # Name of a bound data-parallel mesh axis when the body runs inside a
    # data-sharded device program (shard_map), else None. Batch-statistics
    # layers (BatchNorm) psum over it so a data-sharded micro-batch
    # normalizes by the SAME whole-micro-batch statistics as the unsharded
    # run — the SPMD promise that mesh factorization never changes the math.
    data_axis: Optional[str] = None

    def fold(self, *data: int) -> "StageCtx":
        """Derive a ctx with a key folded over the given integers."""
        if self.key is None:
            return self
        key = self.key
        for d in data:
            key = jax.random.fold_in(key, d)
        return dataclasses.replace(self, key=key)


def _accepts_ctx(fn: Callable) -> bool:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    for p in sig.parameters.values():
        if p.kind == inspect.Parameter.VAR_KEYWORD or p.name == "ctx":
            return True
    return False


@dataclasses.dataclass
class Stage:
    """One pipeline stage: a pure function applied to per-stage params.

    ``fn(params, *inputs, ctx=StageCtx)`` maps the micro-batch payload to the
    stage output (the reference's "partition forward", ``README.md:291-314``).
    Plain functions without a ``ctx`` parameter are adapted automatically;
    params are always passed at call time (pure-program convention).
    """

    fn: Callable
    name: str = "stage"

    def __post_init__(self):
        self._takes_ctx = _accepts_ctx(self.fn)

    def __call__(self, params, *inputs, ctx: Optional[StageCtx] = None):
        if self._takes_ctx:
            return self.fn(params, *inputs, ctx=ctx or StageCtx())
        return self.fn(params, *inputs)


def verify_stages(stages: Sequence[Any]) -> None:
    """No duplicate stage objects (reference ``_verify_module``, ``pipe.py:61-67``)."""
    if len(stages) == 0:
        raise ValueError("pipeline needs at least one stage")
    seen = set()
    for s in stages:
        if id(s) in seen:
            raise ValueError("module with duplicate stages is not supported")
        seen.add(id(s))


def verify_splitting(params_per_stage: Sequence[Any]) -> None:
    """No parameter array shared across stages.

    Reference ``_verify_splitting`` (``pipe.py:70-87``) rejects one parameter
    living on two devices; the SPMD analogue is one buffer appearing in two
    stages' pytrees, which would double-count its gradient.
    """
    seen: dict[int, int] = {}
    for j, params in enumerate(params_per_stage):
        for leaf in jax.tree_util.tree_leaves(params):
            if isinstance(leaf, (jax.Array,)) and leaf.ndim > 0:
                key = id(leaf)
                if key in seen and seen[key] != j:
                    raise ValueError(
                        "module with duplicate parameters on distinct stages is "
                        "not supported"
                    )
                seen[key] = j


def split_balance(n_layers: int, n_stages: int,
                  balance: Optional[Sequence[int]] = None,
                  costs: Optional[Sequence[float]] = None) -> List[int]:
    """Layers-per-stage. Uniform ceil-split default (tutorial ``main.py:139-140``).

    ``balance`` pins the split explicitly (torchgpipe-style). ``costs`` enables
    the profiling-based balancing the reference only advertised
    (``pipe.py:42-58``): a greedy partition equalizing per-stage cost.
    """
    if n_stages <= 0:
        raise BalanceError("number of stages must be positive")
    if balance is not None:
        balance = list(balance)
        if len(balance) != n_stages:
            raise BalanceError(
                f"balance length {len(balance)} != number of stages {n_stages}")
        if sum(balance) != n_layers:
            raise BalanceError(
                f"balance {balance} does not sum to the layer count {n_layers}")
        if any(b <= 0 for b in balance):
            raise BalanceError("all balance entries must be positive")
        return balance
    if n_stages > n_layers:
        raise BalanceError(
            f"cannot split {n_layers} layers into {n_stages} stages")
    if costs is not None:
        if len(costs) != n_layers:
            raise BalanceError("costs length must equal layer count")
        # Greedy contiguous partition: target equal cumulative cost per stage.
        total = float(sum(costs))
        out, acc, taken = [], 0.0, 0
        remaining_stages = n_stages
        for i, c in enumerate(costs):
            acc += c
            taken += 1
            remaining_layers = n_layers - i - 1
            if (acc >= total / n_stages and remaining_stages > 1
                    and remaining_layers >= remaining_stages - 1):
                out.append(taken)
                total -= acc
                n_stages_done = len(out)
                remaining_stages = n_stages - n_stages_done
                acc, taken = 0.0, 0
        out.append(taken)
        while len(out) < n_stages:
            out.append(0)
        if any(b <= 0 for b in out):
            raise BalanceError("cost-based split produced an empty stage")
        return out
    # Fair split: first (n_layers % n_stages) stages take one extra layer, so
    # any n_layers >= n_stages is feasible (e.g. 4 layers / 3 stages -> [2,1,1]).
    base, rem = divmod(n_layers, n_stages)
    return [base + 1 if j < rem else base for j in range(n_stages)]


def partition_sequence(layer_fns: Sequence[Callable],
                       layer_params: Sequence[Any],
                       n_stages: int,
                       balance: Optional[Sequence[int]] = None,
                       costs: Optional[Sequence[float]] = None,
                       ) -> Tuple[List[Stage], List[Any]]:
    """Compose consecutive layers into stage functions.

    The reference's ``_assemble_partition`` wraps children in ``nn.Sequential``
    (``pipe.py:181-188``); here a stage fn is the composition of its layers'
    fns, with the ctx key folded per layer so dropout masks differ layer to
    layer.
    """
    if len(layer_fns) != len(layer_params):
        raise ValueError("layer_fns and layer_params must align")
    bal = split_balance(len(layer_fns), n_stages, balance, costs)
    stages: List[Stage] = []
    params_per_stage: List[Any] = []
    offset = 0
    for j, width in enumerate(bal):
        fns = list(layer_fns[offset:offset + width])
        params = list(layer_params[offset:offset + width])
        offset += width

        def stage_fn(stage_params, *inputs, ctx: StageCtx = StageCtx(),
                     _fns=tuple(fns)):
            out = inputs
            for li, f in enumerate(_fns):
                lctx = ctx.fold(li)
                sub = Stage(f)
                result = sub(stage_params[li], *out, ctx=lctx)
                out = result if isinstance(result, tuple) else (result,)
            return out if len(out) > 1 else out[0]

        stages.append(Stage(stage_fn, name=f"stage{j}"))
        params_per_stage.append(params)
    verify_stages(stages)
    verify_splitting(params_per_stage)
    return stages, params_per_stage
