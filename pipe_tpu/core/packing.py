"""Per-dtype flat packing: heterogeneous pytrees in fixed-capacity buffers.

Two users, one layout:

* **Boundary carrier** (:class:`PackPlan`, used by ``parallel.hetero``): the
  possibly multi-value, shape-varying activation pytree crossing each stage
  boundary is flattened per dtype into 1-D buffers sized to the largest
  boundary — one static ``ppermute`` shape for the whole pipeline.
* **Stage-sharded parameters** (:class:`StageParamPack`): the same trick
  applied to per-stage *parameter* trees. Each stage's pytree flattens into
  per-dtype rows of a ``[n_stages, capacity]`` array sharded
  ``P('stage')`` over the mesh — so each device physically holds ONLY its
  own partition's weights (plus per-dtype padding to the largest stage).
  This is the TPU-native equivalent of the reference moving each partition
  to its own device (``_split_module``, reference ``pipe.py:191-218``, wired
  at ``pipe.py:344-356``): the memory scaling that is the point of pipeline
  parallelism. Replicating every stage's params on every device (the round-2
  design) OOMs exactly at the model scale where pipelining matters.

Inside the compiled program a device's local row unpacks (static slice +
reshape of contiguous memory — XLA aliases these as views) into the stage's
param tree only inside that stage's ``lax.switch`` branch, so the unpack of
other stages' plans never executes. The transpose of unpack is pack
(scatter into the row), so ``jax.grad`` with respect to the packed
representation yields per-dtype ``[n, cap]`` cotangents sharded the same
way — zero communication for stage grads, psum over the data axis inserted
by AD where replication demands it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PackPlan", "StageParamPack"]


class PackPlan:
    """Static layout of one pytree (given as leaf specs) inside per-dtype
    1-D buffers. Used for both boundary carriers and parameter rows."""

    def __init__(self, specs: Sequence[jax.ShapeDtypeStruct]):
        self.specs = list(specs)
        self.sizes = [int(np.prod(s.shape)) if s.shape else 1
                      for s in self.specs]
        self.dtypes = [np.dtype(s.dtype).name for s in self.specs]
        self.per_dtype: dict = {}
        for size, dt in zip(self.sizes, self.dtypes):
            self.per_dtype[dt] = self.per_dtype.get(dt, 0) + size

    def pack(self, values, capacities: dict):
        """values (in spec order) -> {dtype: 1-D padded buffer}."""
        chunks: dict = {dt: [] for dt in capacities}
        for v, dt in zip(values, self.dtypes):
            chunks[dt].append(jnp.ravel(v))
        out = {}
        for dt, cap in capacities.items():
            if chunks[dt]:
                flat = jnp.concatenate(chunks[dt]) if len(chunks[dt]) > 1 \
                    else chunks[dt][0]
                pad = cap - flat.shape[0]
                out[dt] = jnp.pad(flat, (0, pad)) if pad else flat
            else:
                out[dt] = jnp.zeros((cap,), dtype=np.dtype(dt))
        return out

    def unpack(self, carrier: dict):
        offsets: dict = {dt: 0 for dt in carrier}
        values = []
        for spec, size, dt in zip(self.specs, self.sizes, self.dtypes):
            off = offsets[dt]
            flat = jax.lax.slice_in_dim(carrier[dt], off, off + size)
            offsets[dt] = off + size
            values.append(jnp.reshape(flat, spec.shape))
        return values

    def pack_np(self, values, capacities: dict) -> Dict[str, np.ndarray]:
        """Host-side pack: numpy, no device round-trips (used at shard
        construction so 520M-scale packing never materializes on one chip)."""
        chunks: dict = {dt: [] for dt in capacities}
        for v, dt in zip(values, self.dtypes):
            chunks[dt].append(np.ravel(np.asarray(v)))
        out = {}
        for dt, cap in capacities.items():
            npdt = np.dtype(dt)
            buf = np.zeros((cap,), dtype=npdt)
            if chunks[dt]:
                flat = np.concatenate(chunks[dt]) if len(chunks[dt]) > 1 \
                    else chunks[dt][0]
                buf[:flat.shape[0]] = flat
            out[dt] = buf
        return out

    def unpack_np(self, carrier: Dict[str, np.ndarray]):
        offsets: dict = {dt: 0 for dt in carrier}
        values = []
        for spec, size, dt in zip(self.specs, self.sizes, self.dtypes):
            off = offsets[dt]
            flat = carrier[dt][off:off + size]
            offsets[dt] = off + size
            values.append(np.reshape(flat, spec.shape))
        return values


def _leaf_specs(tree) -> List[jax.ShapeDtypeStruct]:
    return [jax.ShapeDtypeStruct(jnp.shape(l), jnp.result_type(l))
            for l in jax.tree_util.tree_leaves(tree)]


class StageParamPack:
    """Plans + capacities mapping per-stage param trees to stage-sharded
    per-dtype ``{dtype: [n, cap]}`` arrays (see module docstring).

    Built from one concrete (or abstract) instance of the per-stage trees;
    thereafter :meth:`shard` / :meth:`unshard` convert representations and
    :meth:`unpack_stage` is the in-program (traced) view used by the
    executor's stage branches.
    """

    def __init__(self, params_per_stage: Sequence[Any]):
        self.n = len(params_per_stage)
        self.treedefs = [jax.tree_util.tree_structure(p)
                         for p in params_per_stage]
        self.plans = [PackPlan(_leaf_specs(p)) for p in params_per_stage]
        self.capacities: Dict[str, int] = {}
        for plan in self.plans:
            for dt, sz in plan.per_dtype.items():
                self.capacities[dt] = max(self.capacities.get(dt, 0), sz)
        if not self.capacities:     # parameterless model: keep one leaf
            self.capacities = {"float32": 1}

    # -- representation conversions (host side) ---------------------------
    def shard(self, mesh, params_per_stage: Sequence[Any],
              stage_axis: str = "stage") -> Dict[str, jax.Array]:
        """Per-dtype ``[n, cap]`` arrays, row ``j`` on stage ``j``'s devices.

        Builds each device's shard directly (``make_array_from_callback``
        over host-packed rows), so no device ever materializes another
        stage's weights — the analogue of ``partition.to(device)`` in the
        reference's ``_split_module`` (``pipe.py:191-218``).
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        if len(params_per_stage) != self.n:
            raise ValueError(
                f"{len(params_per_stage)} stages for an {self.n}-stage pack")
        rows = [plan.pack_np(jax.tree_util.tree_leaves(tree), self.capacities)
                for plan, tree in zip(self.plans, params_per_stage)]
        out = {}
        for dt, cap in self.capacities.items():
            sharding = NamedSharding(mesh, P(stage_axis))

            def cb(index, dt=dt):
                s_slice, c_slice = index
                stages = range(*s_slice.indices(self.n))
                return np.stack([rows[s][dt][c_slice] for s in stages])

            out[dt] = jax.make_array_from_callback((self.n, cap), sharding,
                                                   cb)
        return out

    def unshard(self, packed: Dict[str, jax.Array]) -> List[Any]:
        """Packed ``{dtype: [n, cap]}`` (params OR their grads) back to the
        per-stage trees. Host-side; gathers one stage row at a time and
        returns host (numpy) leaves — re-committing all stages to the
        default device would be exactly the single-chip allocation the
        packed layout exists to avoid. Copies (not views) so the gathered
        row buffers are not pinned by the returned trees."""
        out = []
        for s in range(self.n):
            local = {dt: np.asarray(packed[dt][s])
                     for dt in self.capacities}
            leaves = [np.array(l) for l in self.plans[s].unpack_np(local)]
            out.append(jax.tree_util.tree_unflatten(self.treedefs[s], leaves))
        return out

    def check_packed(self, packed: Dict[str, jax.Array]) -> None:
        """Fail fast when a packed dict does not match this pack's layout
        (wrong Pipe, wrong balance, truncated dict): same dtype keys, every
        buffer shaped ``[n, cap]``. Residual ambiguity: mirror balances
        (e.g. [3,1] vs [1,3] of identical layers) produce byte-identical
        buffer shapes and cannot be distinguished here."""
        if set(packed) != set(self.capacities):
            raise ValueError(
                f"packed params have dtypes {sorted(packed)} but this pack "
                f"expects {sorted(self.capacities)}")
        for dt, cap in self.capacities.items():
            got = tuple(jnp.shape(packed[dt]))
            if got != (self.n, cap):
                raise ValueError(
                    f"packed[{dt!r}] has shape {got}, expected "
                    f"{(self.n, cap)} — params packed by a different "
                    f"Pipe/balance?")

    def replace_stage(self, packed: Dict[str, jax.Array], s: int,
                      new_tree) -> Dict[str, jax.Array]:
        """New packed dict with stage ``s``'s row rebuilt from ``new_tree``
        (traced ops — usable under jit; the other rows alias through)."""
        leaves = jax.tree_util.tree_leaves(new_tree)
        row = self.plans[s].pack(leaves, self.capacities)
        return {dt: packed[dt].at[s].set(row[dt]) for dt in packed}

    # -- in-program views (traced) ----------------------------------------
    def unpack_stage(self, local_rows: Dict[str, jax.Array], s: int):
        """Stage ``s``'s param tree from a device's local ``{dtype: [cap]}``
        row. Static offsets: slice + reshape of contiguous memory, which XLA
        aliases — only the selected switch branch ever executes its unpack."""
        leaves = self.plans[s].unpack(local_rows)
        return jax.tree_util.tree_unflatten(self.treedefs[s], leaves)

    def abstract_tree(self, s: int):
        """Stage ``s``'s params as ShapeDtypeStructs (for eval_shape chains)."""
        return jax.tree_util.tree_unflatten(self.treedefs[s],
                                            list(self.plans[s].specs))

    # -- accounting --------------------------------------------------------
    def per_device_bytes(self) -> int:
        """Bytes each device holds: one row of every per-dtype buffer."""
        return sum(cap * np.dtype(dt).itemsize
                   for dt, cap in self.capacities.items())

    def total_param_bytes(self) -> int:
        return sum(sz * np.dtype(dt).itemsize
                   for plan in self.plans
                   for sz, dt in zip(plan.sizes, plan.dtypes))
