"""Automatic stage balancing: profile layers, split stages by cost.

The reference *advertises* this capability but never shipped it — its
``_recommend_auto_balance`` error text points users at a ``balance_by_time``
that exists only in torchgpipe, not in the torch package (reference
``pipe.py:42-58``; SURVEY §2 "Auto-balance"). Here it is real:

* :func:`profile_times` — per-layer forward (or forward+backward) wall time,
  measured layer-by-layer with host sync;
* :func:`profile_sizes` — per-layer parameter + activation bytes;
* :func:`balance_by_time` / :func:`balance_by_size` — feed the measured
  costs into the contiguous balanced-partition solver
  (:func:`core.partition.split_balance`).

The solver minimizes the bottleneck stage cost over contiguous splits via
binary search + greedy feasibility — optimal for this objective, unlike the
reference lineage's greedy heuristic.
"""

from __future__ import annotations

import statistics
import time
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..ops.layers import Sequential
from .partition import BalanceError, StageCtx

__all__ = ["profile_times", "profile_sizes", "balance_by_time",
           "balance_by_size", "balance_cost", "stage_costs",
           "rebalance_stage_loss"]


def _layer_specs(module: Sequential, params: Sequence[Any], sample) -> List:
    """Input spec for each layer, chained through out_spec."""
    specs = [sample]
    cur = [jax.ShapeDtypeStruct(jnp.shape(sample), jnp.result_type(sample))]
    for layer, p in zip(module, params):
        out = layer.out_spec(p, *cur)
        cur = list(out) if isinstance(out, (tuple, list)) else [out]
        specs.append(cur[0])
    return specs[:-1]


def profile_times(module: Sequential, params: Sequence[Any], sample,
                  *, backward: bool = True, repeat: int = 3,
                  warmup: int = 1,
                  key: Optional[jax.Array] = None) -> List[float]:
    """Measured per-layer step time in seconds (jitted, host-synced).

    torchgpipe's balance_by_time analogue: each layer is jitted and timed in
    isolation on real inputs of the shapes it will see in the pipeline.

    Noise robustness (the planner ranks candidate cuts on these numbers):
    after the compile call, ``warmup`` timed runs are DISCARDED — the first
    post-compile dispatches pay allocator warm-up and host-cache effects —
    and the reported figure is the MEDIAN of the remaining ``repeat``
    samples. A median tolerates one-sided outliers (GC pause, scheduler
    preemption) that a min systematically hides and a mean absorbs.
    """
    key = key if key is not None else jax.random.key(0)
    specs = _layer_specs(module, params, sample)
    times: List[float] = []
    for i, (layer, p, spec) in enumerate(zip(module, params, specs)):
        x = jax.random.normal(jax.random.fold_in(key, i),
                              spec.shape).astype(spec.dtype) \
            if jnp.issubdtype(spec.dtype, jnp.floating) else \
            jnp.zeros(spec.shape, spec.dtype)

        if backward and jax.tree_util.tree_leaves(p):
            def f(p, x, _layer=layer):
                out = _layer.apply(p, x, ctx=StageCtx())
                return jnp.sum(jnp.square(out.astype(jnp.float32)))
            fn = jax.jit(jax.grad(f))
            args = (p, x)
        else:
            def f(p, x, _layer=layer):
                return _layer.apply(p, x, ctx=StageCtx())
            fn = jax.jit(f)
            args = (p, x)

        out = fn(*args)                      # compile
        jax.block_until_ready(out)
        samples: List[float] = []
        for r in range(warmup + repeat):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            if r >= warmup:
                samples.append(dt)
        times.append(statistics.median(samples))
    return times


def profile_sizes(module: Sequential, params: Sequence[Any], sample
                  ) -> List[int]:
    """Per-layer bytes: parameters + output activation (balance_by_size)."""
    specs = _layer_specs(module, params, sample)
    sizes: List[int] = []
    for layer, p, spec in zip(module, params, specs):
        param_bytes = sum(
            a.size * a.dtype.itemsize for a in jax.tree_util.tree_leaves(p)
            if hasattr(a, "dtype"))
        out = layer.out_spec(p, spec)
        outs = out if isinstance(out, (tuple, list)) else [out]
        act_bytes = sum(int(jnp.prod(jnp.asarray(o.shape))) * o.dtype.itemsize
                        for o in outs)
        sizes.append(param_bytes + act_bytes)
    return sizes


def _bottleneck_split(costs: Sequence[float], n_stages: int) -> List[int]:
    """Contiguous split minimizing the max per-stage cost (binary search)."""
    costs = list(costs)
    if n_stages > len(costs):
        raise BalanceError(
            f"cannot split {len(costs)} layers into {n_stages} stages")

    def feasible(cap: float) -> Optional[List[int]]:
        out, acc, taken = [], 0.0, 0
        for i, c in enumerate(costs):
            if c > cap:
                return None
            if acc + c > cap:
                out.append(taken)
                acc, taken = 0.0, 0
            acc += c
            taken += 1
        out.append(taken)
        if len(out) > n_stages:
            return None
        # pad by stealing single layers off the largest groups
        while len(out) < n_stages:
            j = max(range(len(out)), key=lambda k: out[k])
            if out[j] < 2:
                return None
            out[j] -= 1
            out.insert(j + 1, 1)
        return out

    lo, hi = max(costs), sum(costs)
    best = feasible(hi)
    for _ in range(60):
        mid = (lo + hi) / 2
        f = feasible(mid)
        if f is not None:
            best, hi = f, mid
        else:
            lo = mid
    if best is None:
        raise BalanceError("no feasible balanced split")
    return best


def balance_by_time(n_stages: int, module: Sequential,
                    params: Sequence[Any], sample, **profile_kw) -> List[int]:
    """Stage balance from measured per-layer times (torchgpipe parity API)."""
    return _bottleneck_split(
        profile_times(module, params, sample, **profile_kw), n_stages)


def balance_by_size(n_stages: int, module: Sequential,
                    params: Sequence[Any], sample) -> List[int]:
    """Stage balance from parameter+activation bytes (torchgpipe parity API)."""
    return _bottleneck_split(
        profile_sizes(module, params, sample), n_stages)


def rebalance_stage_loss(balance: Sequence[int],
                         costs: Optional[Sequence[float]] = None
                         ) -> List[int]:
    """Re-cut an existing stage balance over one fewer stage.

    The elastic recovery path: a stage died, its layers must be
    redistributed over the ``n - 1`` survivors. The layer sequence is
    unchanged — only the cut points move — so the same contiguous
    bottleneck solver applies, fed either the per-layer ``costs`` the
    caller measured (``profile_times``/``profile_sizes``) or uniform
    unit costs when none are known. Raises :class:`BalanceError` when
    the original balance has fewer than two stages (nothing to fail
    over to).
    """
    n = len(balance)
    if n < 2:
        raise BalanceError(
            f"cannot rebalance a {n}-stage pipeline over stage loss")
    total = sum(int(w) for w in balance)
    if costs is None:
        costs = [1.0] * total
    elif len(costs) != total:
        raise BalanceError(
            f"costs cover {len(costs)} layers but balance sums to {total}")
    return _bottleneck_split(costs, n - 1)


def stage_costs(balance: Sequence[int], costs: Sequence[float]
                ) -> List[float]:
    """Per-stage cost vector of a balance: ``out[j]`` sums the layer costs
    assigned to stage ``j``. The planner feeds this straight into the
    heterogeneous wall model (each stage's op is priced by ITS cost, not
    the bottleneck's); :func:`balance_cost` is its max."""
    if sum(int(w) for w in balance) != len(costs):
        raise BalanceError(
            f"balance sums to {sum(int(w) for w in balance)} layers but "
            f"costs cover {len(costs)}")
    out, off = [], 0
    for w in balance:
        out.append(float(sum(costs[off:off + w])))
        off += w
    return out


def balance_cost(balance: Sequence[int], costs: Sequence[float],
                 *, per_stage: bool = False):
    """Bottleneck (max stage) cost of a balance — lower is better.
    ``per_stage=True`` returns the full per-stage vector instead of the
    scalar (equivalently :func:`stage_costs`)."""
    vec = stage_costs(balance, costs)
    return vec if per_stage else max(vec)
