"""The fleet observability plane: cross-process metrics, distributed
request traces, and the SLO monitor.

PR 13 made replicas real OS processes — and made every child's
``serve.*`` metrics and request events die inside its own interpreter.
This module is the parent-side half of the plane that brings them back:

* **Metrics shipping.** Children snapshot their
  :class:`~.telemetry.MetricsRegistry` as mergeable deltas (counters as
  deltas, gauges last-value, histograms as sparse log2-bucket deltas —
  ``MetricsRegistry.snapshot(mergeable=True)``) and piggyback them on
  the heartbeat cadence as bounded, droppable ``obs`` frames
  (:mod:`..fleet.proc`). :class:`FleetObserver` folds the per-replica
  merged views into labelled per-replica dicts plus one fleet rollup
  registry, with a staleness age per replica. In-process and threaded
  fleets have no wire — the observer reads the shared process registry
  and the engines directly, so one test matrix covers all three
  ``--fleet`` modes.

* **Distributed tracing.** A ``trace_id`` minted at
  ``RequestQueue.submit`` rides the request through placement, retry
  park, KV handoff and failover (including across the process wire).
  The controller and the engines emit ``request``-kind events tagged
  ``trace``/``stage``/``attempts``; child events ship home on obs
  frames; :meth:`FleetObserver.stitch` merges parent + child streams
  into one causally-ordered timeline per request. The order key is
  ``(attempts, stage rank, t)`` — placement attempt number first, so a
  SIGKILL failover reads as ONE trace with TWO placement spans, in
  order, even though the two replicas' clocks are unrelated.

* **SLO monitoring.** :class:`SloMonitor` computes TTFT / end-to-end
  latency percentiles from the merged histograms plus goodput,
  deadline-miss and shed rates, and scores them against declared
  :class:`SloTargets` into a machine-readable verdict dict — the
  planner-feedback hook (ROADMAP item 4). :func:`prometheus_text`
  renders any registry in the Prometheus text exposition format for
  ``apps/serve.py --metrics-port`` and ``tools/fleet_top.py``.

Nothing here imports serve/fleet modules — the observer takes the
controller duck-typed — so the child worker can import
:class:`TraceBuffer` without dragging the control plane into every
replica process.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .events import EventLog
from .telemetry import Counter, EwmaTimer, Gauge, Histogram, \
    MetricsRegistry, get_registry, labelled

__all__ = ["TraceBuffer", "FleetObserver", "SloTargets", "SloMonitor",
           "prometheus_text", "STAGE_RANK"]


# ---------------------------------------------------------------------------
# child-side trace capture


class TraceBuffer:
    """Bounded in-memory :class:`~.events.EventLog` stand-in for replica
    child processes: same recording surface, but records land in a
    deque (oldest dropped at capacity, counted in ``dropped``) that the
    obs shipper drains onto the wire. No file, no fsync — a replica's
    trace events are telemetry, and telemetry is droppable."""

    path = None

    def __init__(self, maxlen: int = 4096):
        self._dq: "deque[Dict[str, Any]]" = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._next_id = 0
        self._t0 = time.perf_counter()
        self.dropped = 0

    def _stack(self) -> List[int]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _push(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._dq) == self._dq.maxlen:
                self.dropped += 1
            self._dq.append(rec)

    def _alloc_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def event(self, kind: str, **attrs: Any) -> None:
        stack = self._stack()
        rec = {"kind": kind, "id": self._alloc_id(),
               "parent": stack[-1] if stack else None,
               "t": time.perf_counter() - self._t0}
        rec.update(attrs)
        self._push(rec)

    @contextlib.contextmanager
    def span(self, kind: str, **attrs: Any):
        stack = self._stack()
        span_id = self._alloc_id()
        parent = stack[-1] if stack else None
        stack.append(span_id)
        t0 = time.perf_counter()
        try:
            yield span_id
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            rec = {"kind": kind, "id": span_id, "parent": parent,
                   "t": t0 - self._t0, "dur": dur}
            rec.update(attrs)
            self._push(rec)

    def step_report(self, report) -> None:
        payload = report.to_json() if hasattr(report, "to_json") else report
        self.event("step_report", **payload)

    def metrics_snapshot(self, registry) -> None:
        self.event("metrics", metrics=registry.snapshot())

    def drain(self) -> List[Dict[str, Any]]:
        """Remove and return everything buffered (oldest first)."""
        with self._lock:
            out = list(self._dq)
            self._dq.clear()
        return out

    def peek(self) -> List[Dict[str, Any]]:
        """Everything buffered (oldest first) WITHOUT draining — what
        an observer holding a live buffer as ``parent_events`` reads,
        so stitching never steals records from the shipper."""
        with self._lock:
            return list(self._dq)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "TraceBuffer":
        return self

    def __exit__(self, *exc) -> None:
        pass


# ---------------------------------------------------------------------------
# trace stitching


# Causal order of one placement cycle. The sort key is
# (attempts, STAGE_RANK, t): the attempt number dominates — stage "t"
# fields come from UNRELATED clocks (parent vs each child) and are only
# comparable within one source — so a failed-over request reads
# queued -> placed(1) -> prefill(1) -> ... -> retry_parked(1) ->
# handoff(1) -> placed(2) -> ... -> delivered, two placement spans in
# one trace.
STAGE_RANK = {"queued": 0, "placed": 1, "prefill": 2, "decode": 3,
              "terminal": 4, "retry_parked": 5, "handoff": 6,
              "delivered": 7}


def _trace_sort_key(rec: Dict[str, Any]) -> Tuple:
    return (int(rec.get("attempts") or 0),
            STAGE_RANK.get(rec.get("stage"), 3),
            float(rec.get("t") or 0.0))


# ---------------------------------------------------------------------------
# the observer


class FleetObserver:
    """Parent-side merge point of the fleet observability plane.

    ``controller`` is a :class:`~..fleet.control.FleetController` (or
    :class:`~..serve.router.Router`), duck-typed: the observer walks
    ``controller.replicas`` and asks each transport for its
    ``obs_view()`` — process transports return the shipped
    ``(registry, age_s, seq, events)`` view; in-process transports
    return None and the observer reads the shared process registry and
    the engine directly (no wire, staleness 0). ``parent_events`` is
    the controller's event-log path (defaults to
    ``controller.events.path`` when that log writes to a file) or an
    already-read list of records — the parent half of every trace.
    """

    def __init__(self, controller, parent_events=None):
        self.controller = controller
        if parent_events is None:
            parent_events = getattr(getattr(controller, "events", None),
                                    "path", None)
        self.parent_events = parent_events

    # -- per-replica views -------------------------------------------------

    def per_replica(self) -> Dict[int, Dict[str, Any]]:
        """One labelled view per replica: health state, load, the
        delivery-synchronized ``tokens_out``/``responses_out`` counters,
        and — for shipped transports — the merged metrics snapshot with
        its staleness age (seconds since the newest obs frame; None
        before the first). In-process replicas read fresh
        (``staleness_s`` 0.0) straight off the engine."""
        out: Dict[int, Dict[str, Any]] = {}
        for rep in self.controller.replicas:
            tr = rep.transport
            view: Dict[str, Any] = {
                "state": rep.state,
                "role": getattr(rep, "role", "mixed"),
                "queue_depth": self._safe(lambda t=tr: t.queue_depth, 0),
                "live_slots": self._safe(lambda t=tr: t.live_slots, 0),
                "tokens_out": int(getattr(tr, "obs_tokens_out", 0)),
                "responses_out": int(getattr(tr, "obs_responses_out", 0)),
            }
            shipped = tr.obs_view()
            if shipped is not None:
                reg, age, seq, _events = shipped
                view.update(shipped=True, staleness_s=age, obs_seq=seq,
                            metrics=reg.snapshot())
            else:
                eng = getattr(tr, "engine", None)
                view.update(shipped=False, staleness_s=0.0, obs_seq=None,
                            metrics=self._inproc_metrics(rep.index))
                if eng is not None:
                    view["queue_depth"] = eng.queue.depth
                    view["live_slots"] = eng.live_slots
            # KV gen-2 directory view: digest count + block occupancy as
            # the controller's placement sees them (heartbeat-stale for
            # shipped transports, fresh in-process); absent for slab
            # replicas and unarmed process fleets
            d = self._safe(lambda t=tr: t.prefix_directory(), None)
            if d:
                view["kv"] = {
                    "digests": len(d.get("digests", ())),
                    "occupancy": d.get("occupancy"),
                    "blocks_free": d.get("blocks_free"),
                    "blocks_total": d.get("blocks_total"),
                }
            # durable-journal lag: seconds since the controller's last
            # fsync'd lifecycle record (None for journal-less fleets) —
            # fleet-wide, repeated per row so fleet_top can render it
            jr = getattr(self.controller, "journal", None)
            if jr is not None:
                view["journal_lag_s"] = self._safe(
                    lambda j=jr: j.fsync_age_s, None)
            out[rep.index] = view
        return out

    @staticmethod
    def _safe(fn, default):
        try:
            return fn()
        except Exception:
            return default

    @staticmethod
    def _inproc_metrics(index: int) -> Dict[str, Any]:
        """The shared process registry's per-replica series for one
        in-process replica: every labelled instrument carrying
        ``replica=<index>``."""
        needle_mid = f"replica={index},"
        needle_end = f"replica={index}}}"
        snap = get_registry().snapshot()
        return {name: val for name, val in snap.items()
                if "{" in name and (needle_mid in name.split("{", 1)[1]
                                    or needle_end in name.split("{", 1)[1])}

    # -- fleet rollup ------------------------------------------------------

    def rollup(self) -> MetricsRegistry:
        """One merged registry for the whole fleet: the parent process
        registry (fleet counters; for in-process fleets also every
        replica's engine counters — they share it) folded together with
        each shipped replica registry. Histograms merge bucket-wise, so
        fleet percentiles are computed over every replica's
        observations."""
        out = MetricsRegistry()
        out.merge_snapshot(get_registry().snapshot(mergeable=True, base={}))
        for rep in self.controller.replicas:
            shipped = rep.transport.obs_view()
            if shipped is not None:
                out.merge_snapshot(
                    shipped[0].snapshot(mergeable=True, base={}))
        return out

    def role_rollup(self) -> Dict[str, Dict[str, Any]]:
        """Per-role aggregation of the per-replica views — the number
        disaggregation is judged by: the prefill pool's TTFT and the
        decode pool's token output split out instead of averaged into
        one fleet-wide blur. Per role: replica/HEALTHY counts, summed
        load and output counters, bucket-merged ``ttft_sec`` /
        ``token_sec`` means where a replica's metrics view carries them
        (shipped replicas always do; in-process replicas share one
        unlabelled registry, so their phase histograms can't be split
        and read as None), plus the parent-side
        ``serve.fleet.handoff_requests{role=...}`` counter."""
        per = self.per_replica()
        out: Dict[str, Dict[str, Any]] = {}
        for rep in self.controller.replicas:
            view = per[rep.index]
            agg = out.setdefault(view["role"], {
                "replicas": 0, "healthy": 0, "tokens_out": 0,
                "responses_out": 0, "queue_depth": 0, "live_slots": 0,
                "_ttft": None, "_token_sec": None})
            agg["replicas"] += 1
            if view["state"] == "healthy":
                agg["healthy"] += 1
            for k in ("tokens_out", "responses_out", "queue_depth",
                      "live_slots"):
                agg[k] += int(view[k] or 0)
            m = view.get("metrics") or {}
            for key, slot in (("serve.engine.ttft_sec", "_ttft"),
                              ("serve.engine.token_sec", "_token_sec")):
                s = m.get(key)
                if isinstance(s, dict) and s.get("count"):
                    cur = agg[slot]
                    if cur is None:
                        agg[slot] = {"count": int(s["count"]),
                                     "sum": float(s.get("sum", 0.0))}
                    else:
                        cur["count"] += int(s["count"])
                        cur["sum"] += float(s.get("sum", 0.0))
        snap = get_registry().snapshot()
        for role, agg in out.items():
            for slot, name in (("_ttft", "ttft_mean_s"),
                               ("_token_sec", "token_mean_s")):
                s = agg.pop(slot)
                agg[name] = (s["sum"] / s["count"]) if s else None
            agg["handoff_requests"] = int(snap.get(
                labelled("serve.fleet.handoff_requests", role=role), 0))
        return out

    def reconcile(self) -> Dict[str, Any]:
        """The delivered-token reconciliation the drill asserts: the
        per-replica ``tokens_out`` counters (bumped at the instant each
        terminal response crossed into the control plane) must sum to
        the parent-observed delivered total — exactly-once made
        visible in telemetry. A disaggregated controller additionally
        reports the shadow tokens it consumed (each prefill phase's
        one-token terminal, counted by the prefill replica's transport
        but never client-delivered); they sit on the delivered side of
        the balance."""
        per = {rep.index: int(getattr(rep.transport, "obs_tokens_out", 0))
               for rep in self.controller.replicas}
        delivered = sum(len(r.tokens)
                        for r in self.controller._responses.values())
        shadow = int(getattr(self.controller, "obs_shadow_tokens", 0))
        total = sum(per.values())
        return {"per_replica_tokens_out": per, "tokens_out_sum": total,
                "delivered_tokens": delivered, "shadow_tokens": shadow,
                "reconciled": total == delivered + shadow}

    # -- trace stitching ---------------------------------------------------

    def _parent_records(self) -> List[Dict[str, Any]]:
        src = self.parent_events
        if src is None:
            return []
        if isinstance(src, str):
            return EventLog.read(src)
        if hasattr(src, "peek"):       # a live TraceBuffer: non-mutating
            return src.peek()
        return list(src)

    def stitch(self) -> Dict[str, List[Dict[str, Any]]]:
        """Merge the parent event log with every replica's shipped
        trace events into one causally-ordered timeline per request,
        keyed by ``trace_id`` (requests predating a trace id group
        under ``req:<id>``). Each record gains ``src`` ("parent" or
        "replica<i>"); ordering is ``(attempts, stage rank, t)`` — see
        :data:`STAGE_RANK` for why wall-clock alone cannot order a
        cross-process trace."""
        streams: List[Tuple[str, List[Dict[str, Any]]]] = [
            ("parent", self._parent_records())]
        for rep in self.controller.replicas:
            shipped = rep.transport.obs_view()
            if shipped is not None:
                streams.append((f"replica{rep.index}", shipped[3]))
        traces: Dict[str, List[Dict[str, Any]]] = {}
        for src, records in streams:
            for rec in records:
                trace = rec.get("trace")
                if trace is None:
                    if rec.get("kind") != "request" \
                            or rec.get("request") is None:
                        continue
                    trace = f"req:{rec['request']}"
                tagged = dict(rec, src=src, trace=trace)
                traces.setdefault(trace, []).append(tagged)
        for recs in traces.values():
            recs.sort(key=_trace_sort_key)
        return traces

    def stitch_by_request(self) -> Dict[int, List[Dict[str, Any]]]:
        """:meth:`stitch` re-keyed by request id (the bench/test
        handle). A request id maps to exactly ONE trace — trace ids are
        minted once and survive failover — so this is a bijection over
        delivered requests; the quick-drill assertion in ``bench.py``
        leans on that."""
        out: Dict[int, List[Dict[str, Any]]] = {}
        for recs in self.stitch().values():
            rids = {r.get("request") for r in recs
                    if r.get("request") is not None}
            for rid in rids:
                out.setdefault(int(rid), []).extend(
                    [r for r in recs if r.get("request") == rid])
        for recs in out.values():
            recs.sort(key=_trace_sort_key)
        return out

    def write_stitched(self, path: str) -> int:
        """Write the stitched timelines as JSONL — records grouped by
        trace, causally ordered within each — and return the record
        count."""
        traces = self.stitch()
        n = 0
        with open(path, "w") as f:
            for trace in sorted(traces):
                for rec in traces[trace]:
                    f.write(json.dumps(rec) + "\n")
                    n += 1
        return n


# ---------------------------------------------------------------------------
# SLO monitor


@dataclasses.dataclass
class SloTargets:
    """Declared service-level objectives. None disables a check.
    Latency targets are seconds; rate targets are fractions of
    delivered requests (goodput = ok / delivered, so 0.95 means at
    most 5% of terminals may be non-ok)."""

    ttft_p50_s: Optional[float] = None
    ttft_p99_s: Optional[float] = None
    e2e_p99_s: Optional[float] = None
    goodput_min: Optional[float] = None
    deadline_miss_max: Optional[float] = None
    shed_max: Optional[float] = None


class SloMonitor:
    """Scores a merged fleet registry against :class:`SloTargets`.

    The verdict dict is the machine-readable planner hook::

        {"ok": bool, "violations": [{"slo", "target", "observed"}, ...],
         "targets": {...}, "observed": {"ttft_p50_s", "ttft_p99_s",
         "e2e_p99_s", "goodput", "deadline_miss_rate", "shed_rate",
         "delivered", "ok_count"}}

    Percentiles come from the merged log2 histograms, so they are
    upper-edge estimates (≤ 2x true) over EVERY replica's
    observations, not one process's view.
    """

    def __init__(self, targets: Optional[SloTargets] = None):
        self.targets = targets or SloTargets()

    def observe(self, registry: MetricsRegistry) -> Dict[str, Any]:
        ttft = registry.histogram("serve.engine.ttft_sec")
        e2e = registry.histogram("serve.engine.e2e_sec")
        delivered = registry.counter("serve.fleet.delivered").value
        ok = registry.counter("serve.fleet.ok").value
        timed_out = registry.counter("serve.engine.timed_out").value
        shed = registry.counter("serve.engine.shed").value
        denom = max(delivered, 1)
        return {
            "ttft_p50_s": ttft.percentile(0.50),
            "ttft_p99_s": ttft.percentile(0.99),
            "e2e_p99_s": e2e.percentile(0.99),
            "goodput": ok / denom,
            "deadline_miss_rate": timed_out / denom,
            "shed_rate": shed / denom,
            "delivered": delivered,
            "ok_count": ok,
        }

    def verdict(self, registry: MetricsRegistry) -> Dict[str, Any]:
        obs = self.observe(registry)
        t = self.targets
        checks = [
            ("ttft_p50_s", t.ttft_p50_s, obs["ttft_p50_s"], "max"),
            ("ttft_p99_s", t.ttft_p99_s, obs["ttft_p99_s"], "max"),
            ("e2e_p99_s", t.e2e_p99_s, obs["e2e_p99_s"], "max"),
            ("goodput_min", t.goodput_min, obs["goodput"], "min"),
            ("deadline_miss_max", t.deadline_miss_max,
             obs["deadline_miss_rate"], "max"),
            ("shed_max", t.shed_max, obs["shed_rate"], "max"),
        ]
        violations = []
        for slo, target, observed, sense in checks:
            if target is None:
                continue
            bad = observed > target if sense == "max" else observed < target
            if bad:
                violations.append({"slo": slo, "target": target,
                                   "observed": observed})
        return {"ok": not violations, "violations": violations,
                "targets": {k: v for k, v in
                            dataclasses.asdict(t).items() if v is not None},
                "observed": obs}


# ---------------------------------------------------------------------------
# Prometheus text exposition


def _prom_name(name: str) -> Tuple[str, str]:
    """Split a registry name into a Prometheus metric name + label
    block. ``serve.fleet.replica.state{replica=0}`` →
    (``serve_fleet_replica_state``, ``{replica="0"}``); label values
    un-escape the :func:`~.telemetry.labelled` escaping and re-quote."""
    labels = ""
    if "{" in name and name.endswith("}"):
        name, body = name.split("{", 1)
        body = body[:-1]
        parts, cur, esc = [], "", False
        for ch in body:
            if esc:
                cur += ch
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == ",":
                parts.append(cur)
                cur = ""
            else:
                cur += ch
        if cur:
            parts.append(cur)
        rendered = []
        for part in parts:
            k, _, v = part.partition("=")
            v = v.replace("\\", "\\\\").replace('"', '\\"')
            rendered.append(f'{k}="{v}"')
        labels = "{" + ",".join(rendered) + "}"
    base = "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in name)
    return base, labels


def prometheus_text(registry: MetricsRegistry) -> str:
    """Render a registry in the Prometheus text exposition format
    (v0.0.4): counters/gauges as samples, timers as ``_count``/``_sum``
    plus an ``_ewma`` gauge, histograms as cumulative ``_bucket{le=}``
    series over the shared log2 edges plus ``_count``/``_sum``."""
    lines: List[str] = []
    with registry._lock:
        items = sorted(registry._instruments.items())
    for name, inst in items:
        base, labels = _prom_name(name)
        if isinstance(inst, Counter):
            lines.append(f"# TYPE {base} counter")
            lines.append(f"{base}{labels} {inst.value}")
        elif isinstance(inst, Gauge):
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base}{labels} {inst.value}")
        elif isinstance(inst, EwmaTimer):
            lines.append(f"# TYPE {base} summary")
            lines.append(f"{base}_count{labels} {inst.count}")
            lines.append(f"{base}_sum{labels} {inst.total}")
            lines.append(f"{base}_ewma{labels} {inst.ewma}")
        elif isinstance(inst, Histogram):
            lines.append(f"# TYPE {base} histogram")
            cum = 0
            for i, edge in enumerate(Histogram._EDGES):
                cum += inst.counts[i]
                le = labels[:-1] + "," if labels else "{"
                lines.append(f'{base}_bucket{le}le="{edge:g}"}} {cum}')
            le = labels[:-1] + "," if labels else "{"
            lines.append(f'{base}_bucket{le}le="+Inf"}} {inst.count}')
            lines.append(f"{base}_count{labels} {inst.count}")
            lines.append(f"{base}_sum{labels} {inst.sum}")
    return "\n".join(lines) + ("\n" if lines else "")
