"""Multi-stage measured-bubble probe on the virtual CPU mesh.

``python -m pipe_tpu.obs.bubble_probe [n_stages] [chunks] [--schedules]
[--transport]`` forces the 8-device CPU platform, times one compiled
pipeline train step at ``m`` and ``2m`` micro-batches (per-micro-batch work
held constant), and prints one JSON line with the measured and analytic
bubble; ``--schedules`` adds head-to-head table-executor timings (1f1b vs
zb-h1) with each table's analytic idle fraction, and ``--transport`` adds
the packed overlapped-transport 1f1b row (with per-transport measured
bubbles) next to the serialized one. bench.py runs this (via
``tools/multistage_probe.py --quick``) as a
subprocess so the single-chip TPU benchmark can still report a REAL
multi-stage bubble measurement (VERDICT r1 #6: the reference author verified
the schedule with profiler traces, ``/root/reference/README.md:559-567``;
the single real chip can't host a ppermute ring, the virtual mesh can).
"""

from __future__ import annotations

import json
import sys
import time


def main(n_stages: int = 4, chunks: int = 8,
         compare_schedules: bool = False, d_model: int = 256,
         d_ff: int = 512, seq_len: int = 64, skip_slope: bool = False,
         iters: int = 4, compare_transport: bool = False) -> dict:
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

    import jax
    import jax.numpy as jnp

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.core.schedule import bubble_fraction
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.obs.meters import measured_bubble_slope
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params

    cfg = LMConfig(vocab=512, d_model=d_model, nhead=4, d_ff=d_ff,
                   n_layers=n_stages, seq_len=seq_len, dropout=0.0)
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    model = PipelinedLM(cfg, n_stages)
    sp, prep, postp = model.init(jax.random.key(0))
    sp = stack_stage_params(sp)
    spmd = SpmdPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                        post_fn=model.loss_post_fn, post_with_batch=True,
                        checkpoint="never")

    mb_rows = 4

    def make_batch(m: int):
        """One probe batch: m micro-batches of mb_rows, shared recipe for
        the slope timings AND the schedule comparison (same workload)."""
        tokens = jax.random.randint(jax.random.key(1),
                                    (mb_rows * m, cfg.seq_len),
                                    0, cfg.vocab, jnp.int32)
        return mb.stack_scatter(
            {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)

    def step_time(m: int, iters: int = iters) -> float:
        x, _ = make_batch(m)

        @jax.jit
        def loss_grad(sp, x):
            def f(sp):
                return jnp.mean(spmd(sp, prep, postp, x, train=True))
            return jax.value_and_grad(f)(sp)

        l, g = loss_grad(sp, x)
        jax.block_until_ready((l, g))
        t0 = time.perf_counter()
        for _ in range(iters):
            l, g = loss_grad(sp, x)
        jax.block_until_ready((l, g))
        return (time.perf_counter() - t0) / iters

    m = chunks
    out = {
        "platform": "cpu8",
        "n_stages": n_stages,
        "chunks": m,
        "d_model": d_model,
        "analytic_bubble": round(bubble_fraction(m, n_stages), 4),
    }
    if not skip_slope:
        t_m, t_2m = step_time(m), step_time(2 * m)
        out.update({
            "t_m_sec": round(t_m, 5),
            "t_2m_sec": round(t_2m, 5),
            "measured_bubble": round(
                measured_bubble_slope(t_m, t_2m, m), 4),
        })

    if compare_schedules:
        # Head-to-head step timings of the table executor per schedule at
        # the same workload (never mode so zb-h1's stored-vjp DCE split
        # applies), next to each table's analytic idle fraction. The CPU
        # mesh carries real per-cycle machinery overhead, so the analytic
        # column is the schedule property and the seconds are the honest
        # end-to-end number on THIS platform.
        from pipe_tpu.obs.meters import measured_bubble_slope
        from pipe_tpu.parallel.scheduled import ScheduledPipeline

        scheds = {}
        # "1f1b+policy" is the HEADLINE training program (BENCH_r03:
        # except_last + dots_saveable) running on the real multi-device
        # stage axis — the configuration the single-chip bench reports,
        # proven here to execute on the very topology it is sold for.
        configs = [
            ("1f1b", dict(checkpoint="never", schedule="1f1b")),
            ("1f1b+policy", dict(checkpoint="except_last", schedule="1f1b",
                                 remat_policy=jax.checkpoint_policies
                                 .dots_saveable)),
            ("zb-h1", dict(checkpoint="never", schedule="zb-h1")),
            # The split-table rows: auto-derived structural B/W split
            # (core/remat.py) so B runs a params-constant vjp and W only
            # the tap x cotangent contractions — total backward work
            # equals the fused backward's, unlike the legacy stored-vjp
            # row above that transposes twice.
            ("zb-h1-split", dict(checkpoint="never", schedule="zb-h1",
                                 split_stage="auto")),
            ("zb-h2-split", dict(checkpoint="never", schedule="zb-h2",
                                 split_stage="auto")),
        ]
        if compare_transport:
            # Same workload with the packed, software-pipelined boundary
            # transport forced on (auto keeps it off on cpu) — the
            # serialized "1f1b" row next to it is the side-by-side the
            # bench records every round.
            configs.insert(1, ("1f1b-overlap",
                               dict(checkpoint="never", schedule="1f1b",
                                    overlap_transport=True)))
            # Phase-compiled rows (forced: auto keeps phased off on cpu).
            # CAVEAT for reading these on cpu8: the virtual mesh serializes
            # all devices onto one host core, so the phased ramps' masked
            # cycles — where an idle device executes the cycle's op on
            # garbage and discards it, free on real parallel hardware —
            # show up as REAL extra host work. The cpu8 phased rows
            # therefore upper-bound the phased program's cost; the
            # switch-free steady state is the part that transfers.
            configs += [
                ("1f1b-phase", dict(checkpoint="never", schedule="1f1b",
                                    phase_compile=True)),
                ("zb-h1-phase", dict(checkpoint="never", schedule="zb-h1",
                                     phase_compile=True)),
                ("zb-h1-split-phase",
                 dict(checkpoint="never", schedule="zb-h1",
                      split_stage="auto", phase_compile=True)),
            ]

        def step_time_sched(pipe, mm: int) -> float:
            xx, nr = make_batch(mm)
            ww = mb.valid_row_mask(xx, nr)
            lg = jax.jit(lambda sp: pipe.loss_and_grad(
                sp, prep, postp, xx, ww))
            jax.block_until_ready(lg(sp))
            t0 = time.perf_counter()
            for _ in range(iters):
                out_lg = lg(sp)
            jax.block_until_ready(out_lg)
            return (time.perf_counter() - t0) / iters

        for name, kw_s in configs:
            pipe = ScheduledPipeline(
                mesh, model.stage_fn, pre_fn=model.pre_fn,
                post_fn=model.loss_post_fn, **kw_s)
            sec = step_time_sched(pipe, m)
            scheds[name] = {
                "sec_per_step": round(sec, 5),
                # __post_init__ already built the Schedule; reuse it
                "analytic_bubble": round(
                    pipe.schedule.bubble(m, n_stages), 4),
            }
            if kw_s.get("phase_compile"):
                prog = pipe._phase_program(m)
                scheds[name]["phase"] = (
                    {"unrolled_cycles": prog.unrolled_cycles,
                     "scan_cycles": prog.scan_cycles}
                    if prog is not None else "rejected")
            if compare_transport and name in ("1f1b", "1f1b-overlap"):
                # per-transport measured bubble from the same m/2m slope
                # the headline probe uses, but through the TABLE executor
                # so comm/compute overlap shows up in the number
                sec_2m = step_time_sched(pipe, 2 * m)
                scheds[name]["measured_bubble"] = round(
                    measured_bubble_slope(sec, sec_2m, m), 4)
        out["schedules"] = scheds
    return out


if __name__ == "__main__":
    args = sys.argv[1:]
    cmp_scheds = "--schedules" in args
    skip_slope = "--no-slope" in args
    cmp_transport = "--transport" in args
    kw = {}
    pos = []
    for a in args:
        if a in ("--schedules", "--no-slope", "--transport"):
            continue
        if "=" in a and a.startswith("--"):
            k, v = a[2:].split("=", 1)
            kw[k.replace("-", "_")] = int(v)
        else:
            pos.append(a)
    n = int(pos[0]) if len(pos) > 0 else 4
    m = int(pos[1]) if len(pos) > 1 else 8
    print(json.dumps(main(n, m, compare_schedules=cmp_scheds,
                          skip_slope=skip_slope,
                          compare_transport=cmp_transport, **kw)))
