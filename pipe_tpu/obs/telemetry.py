"""Process-local metrics registry and per-step reporting.

The reference stack was studied through its tracing surface alone
(``record_function`` spans + TensorBoard traces); pipe_tpu folds that and
the scalar side into one layer:

* :class:`MetricsRegistry` — counters, gauges, EWMA timers, log-scale
  histograms. Process-local, dependency-free, and a cheap no-op when
  disabled: a disabled registry hands out shared null instruments whose
  methods do nothing (no allocation, no clock reads), so hot paths can
  instrument unconditionally.
* :class:`StepReport` — one training step folded into the fields the
  committed ``BENCH_*.json`` artifacts carry (tokens/sec, MFU/HFU,
  analytic + measured bubble, per-device memory peaks), so every round's
  numbers are comparable whether they came from ``bench.py`` or a live
  training run.
* the MFU arithmetic (:func:`train_flops_per_token`,
  :func:`peak_flops_per_chip`) — moved here from ``bench.py`` so serving
  and training paths share one FLOPs model.

Export goes through two sinks: ``tb_writer.ScalarWriter`` (TensorBoard)
and the JSONL event log (:mod:`.events`).
"""

from __future__ import annotations

import bisect
import contextlib
import dataclasses
import math
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "EwmaTimer", "Histogram", "MetricsRegistry",
    "StepReport", "get_registry", "set_registry", "null_registry",
    "labelled", "percentile_exact", "host_overhead_per_token",
    "train_flops_per_token", "peak_flops_per_chip", "device_memory_peaks",
]


def _escape_label(value) -> str:
    """Escape the characters that carry structure in a labelled name
    (``\\ . { } , =``) so replica ids like ``host.1`` or ``a,b=c``
    cannot collide with a differently-labelled instrument or with the
    ``.``-suffixed export keys ``scalars()`` derives."""
    s = str(value)
    for ch in ("\\", ".", "{", "}", ",", "="):
        s = s.replace(ch, "\\" + ch)
    return s


def labelled(name: str, **labels) -> str:
    """Canonical labelled-instrument name: ``name{k=v,k2=v2}`` with keys
    sorted, so every call site derives the same registry key. The
    registry itself stays flat (one instrument per string) — labels are
    a *naming convention*, which keeps the null-registry fast path and
    the ``scalars()`` dump untouched while letting fleet consumers
    filter per-replica series by prefix (e.g.
    ``serve.fleet.replica.queue_depth{replica=2}``). Label *values* are
    escaped (:func:`_escape_label`) so structured replica ids stay
    collision-safe; plain ints and simple strings pass through
    unchanged."""
    if not labels:
        return name
    body = ",".join(f"{k}={_escape_label(labels[k])}" for k in sorted(labels))
    return f"{name}{{{body}}}"


# --------------------------------------------------------------------------
# Instruments
# --------------------------------------------------------------------------

class Counter:
    """Monotonic count (dispatches, cache hits, tokens, ...)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins scalar (tokens/sec, uniform_fastpath 0/1, ...)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class EwmaTimer:
    """Duration tracker: count/total plus an exponential moving average.

    The EWMA (default alpha 0.1 ≈ a ~10-observation horizon) is the
    steady-state per-step number; ``total/count`` includes warmup/compile.
    """

    __slots__ = ("alpha", "count", "total", "ewma", "last")

    def __init__(self, alpha: float = 0.1):
        self.alpha = alpha
        self.count = 0
        self.total = 0.0
        self.ewma = 0.0
        self.last = 0.0

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.total += seconds
        self.last = seconds
        self.ewma = seconds if self.count == 1 else (
            self.alpha * seconds + (1.0 - self.alpha) * self.ewma)

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)


class Histogram:
    """Log-scale latency histogram (powers of 2 from ~1 µs to ~1 h).

    Fixed 42-bucket layout keeps ``observe`` a bisect + increment; the
    percentile estimate returns the upper edge of the covering bucket
    (≤ 2x the true value — plenty for latency-distribution shape).
    """

    __slots__ = ("counts", "count", "sum", "min", "max")

    _EDGES = [2.0 ** e for e in range(-20, 12)]   # 0.95 µs .. 2048 s

    def __init__(self):
        self.counts = [0] * (len(self._EDGES) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        self.counts[bisect.bisect_left(self._EDGES, seconds)] += 1
        self.count += 1
        self.sum += seconds
        self.min = min(self.min, seconds)
        self.max = max(self.max, seconds)

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    def percentile(self, q: float) -> float:
        """Upper-edge estimate of the q-quantile (q in [0, 1])."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self._EDGES[i] if i < len(self._EDGES) else self.max
        return self.max

    def summary(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0}
        return {"count": self.count, "sum": self.sum,
                "mean": self.sum / self.count,
                "min": self.min, "max": self.max,
                "p50": self.percentile(0.50), "p90": self.percentile(0.90),
                "p99": self.percentile(0.99)}


class _NullInstrument:
    """Shared do-nothing stand-in for every instrument type. ``time()``
    reads no clock, so a disabled registry costs one attribute call per
    instrumentation site and nothing else."""

    __slots__ = ()
    value = 0
    count = 0
    total = 0.0
    ewma = 0.0
    last = 0.0
    sum = 0.0

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, seconds: float) -> None:
        pass

    def time(self):
        return _NULL_CONTEXT

    def percentile(self, q: float) -> float:
        return 0.0

    def summary(self) -> Dict[str, float]:
        return {"count": 0}


_NULL_CONTEXT = contextlib.nullcontext()
NULL_INSTRUMENT = _NullInstrument()


# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------

class MetricsRegistry:
    """Named-instrument store. ``counter/gauge/timer/histogram`` create on
    first use and return the same object thereafter; a disabled registry
    returns the shared :data:`NULL_INSTRUMENT` and records nothing."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._instruments: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, factory):
        if not self.enabled:
            return NULL_INSTRUMENT
        inst = self._instruments.get(name)
        if inst is None:
            with self._lock:
                inst = self._instruments.setdefault(name, factory())
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def timer(self, name: str, alpha: float = 0.1) -> EwmaTimer:
        return self._get(name, lambda: EwmaTimer(alpha))

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self, *, mergeable: bool = False,
                 base: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """All instruments as plain data.

        Default form (``mergeable=False``): histograms/timers as summary
        dicts, counters/gauges as raw values — the human-readable shape
        the event log and bench artifacts record.

        ``mergeable=True`` emits the *wire* form the fleet obs plane
        ships between processes: typed records that another registry can
        fold in with :meth:`merge_snapshot` — counters as **deltas**
        (``{"k": "c", "d": n}``), gauges as last-value
        (``{"k": "g", "v": x}``), timers as count/total deltas plus
        last-value ewma (``{"k": "t", ...}``), histograms as sparse
        per-bucket **count deltas** over the shared log2 edges
        (``{"k": "h", "b": [[bucket, d], ...], ...}``) so percentile
        shape survives merging. ``base`` is the caller's delta ledger (a
        mutable dict, updated in place): pass the same dict every call
        and each snapshot carries only what changed since the last one.
        Zero-delta instruments are omitted, which bounds frame size on
        quiet replicas.
        """
        if mergeable:
            return self._mergeable_snapshot(base if base is not None else {})
        out: Dict[str, Any] = {}
        for name, inst in sorted(self._instruments.items()):
            if isinstance(inst, (Counter, Gauge)):
                out[name] = inst.value
            elif isinstance(inst, EwmaTimer):
                out[name] = {"count": inst.count, "total": inst.total,
                             "ewma": inst.ewma, "last": inst.last}
            else:
                out[name] = inst.summary()
        return out

    def _mergeable_snapshot(self, base: Dict[str, Any]) -> Dict[str, Any]:
        # shipped from a telemetry thread while the tick thread creates
        # instruments: copy the name->instrument map under the lock
        with self._lock:
            items = sorted(self._instruments.items())
        out: Dict[str, Any] = {}
        for name, inst in items:
            if isinstance(inst, Counter):
                prev = base.get(name, 0)
                if inst.value != prev:
                    out[name] = {"k": "c", "d": inst.value - prev}
                    base[name] = inst.value
            elif isinstance(inst, Gauge):
                if base.get(name) != inst.value:
                    out[name] = {"k": "g", "v": inst.value}
                    base[name] = inst.value
            elif isinstance(inst, EwmaTimer):
                pc, pt = base.get(name, (0, 0.0))
                if inst.count != pc:
                    out[name] = {"k": "t", "dc": inst.count - pc,
                                 "dt": inst.total - pt, "ewma": inst.ewma,
                                 "last": inst.last, "alpha": inst.alpha}
                    base[name] = (inst.count, inst.total)
            elif isinstance(inst, Histogram):
                prev_counts = base.get(name)
                if prev_counts is None:
                    prev_counts = [0] * len(inst.counts)
                buckets = [[i, c - prev_counts[i]]
                           for i, c in enumerate(inst.counts)
                           if c != prev_counts[i]]
                if buckets:
                    dn = sum(d for _, d in buckets)
                    ds = inst.sum - base.get(name + "\0sum", 0.0)
                    out[name] = {"k": "h", "b": buckets, "dn": dn, "ds": ds,
                                 "min": (None if inst.min is math.inf
                                         else inst.min),
                                 "max": inst.max}
                    base[name] = list(inst.counts)
                    base[name + "\0sum"] = inst.sum
        return out

    def merge_snapshot(self, snap: Dict[str, Any]) -> None:
        """Fold a ``snapshot(mergeable=True)`` dict from another registry
        (typically another process's) into this one: counter deltas add,
        gauges last-write-win, timer count/total add (ewma/last taken
        from the source — the shipper's steady-state view), histogram
        bucket deltas add bucket-wise so merged percentiles stay exact
        at bucket resolution. Instruments are created on first sight;
        merging into a disabled registry is a no-op."""
        if not self.enabled:
            return
        for name, rec in snap.items():
            kind = rec.get("k") if isinstance(rec, dict) else None
            if kind == "c":
                self.counter(name).inc(rec["d"])
            elif kind == "g":
                self.gauge(name).set(rec["v"])
            elif kind == "t":
                t = self.timer(name, rec.get("alpha", 0.1))
                t.count += rec["dc"]
                t.total += rec["dt"]
                t.ewma = rec["ewma"]
                t.last = rec["last"]
            elif kind == "h":
                h = self.histogram(name)
                for i, d in rec["b"]:
                    h.counts[i] += d
                h.count += rec["dn"]
                h.sum += rec["ds"]
                if rec.get("min") is not None:
                    h.min = min(h.min, rec["min"])
                h.max = max(h.max, rec["max"])

    def scalars(self) -> Dict[str, float]:
        """Flat name → float view for ``ScalarWriter`` export (timer →
        ``name.ewma``, histogram → ``name.p50``/``name.p99``)."""
        out: Dict[str, float] = {}
        for name, inst in sorted(self._instruments.items()):
            if isinstance(inst, (Counter, Gauge)):
                out[name] = float(inst.value)
            elif isinstance(inst, EwmaTimer):
                if inst.count:
                    out[f"{name}.ewma"] = inst.ewma
            elif inst.count:
                out[f"{name}.p50"] = inst.percentile(0.50)
                out[f"{name}.p99"] = inst.percentile(0.99)
        return out

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()


_default_registry = MetricsRegistry(enabled=True)
_NULL_REGISTRY = MetricsRegistry(enabled=False)


def get_registry() -> MetricsRegistry:
    """The process-local default registry (enabled unless replaced)."""
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process default (tests, or ``null_registry()`` to disable
    all default-registry instrumentation). Returns the previous one."""
    global _default_registry
    prev, _default_registry = _default_registry, registry
    return prev


def null_registry() -> MetricsRegistry:
    """The shared disabled registry — every instrument is a no-op."""
    return _NULL_REGISTRY


def percentile_exact(values, q: float) -> float:
    """Exact q-quantile (nearest-rank, q in [0, 1]) of raw samples.

    :class:`Histogram` trades precision for O(1) memory — its percentile
    is a power-of-2 upper edge, up to 2x above the true value. Benchmark
    artifacts (``tools/serve_bench.py`` TTFT numbers) keep the raw
    samples and use this instead, so committed p50/p99 are exact."""
    vals = sorted(values)
    if not vals:
        return 0.0
    rank = min(len(vals), max(1, math.ceil(q * len(vals))))
    return float(vals[rank - 1])


def host_overhead_per_token(registry: Optional[MetricsRegistry] = None
                            ) -> float:
    """Cumulative host-side serve overhead per emitted token, in seconds.

    ``ServeEngine.tick`` accumulates every second of a tick NOT spent
    inside the backend decode launch into the
    ``serve.engine.host_sec`` timer (reap + admission checks + token
    readout + gauge upkeep), and counts emitted tokens in
    ``serve.engine.tokens``; their ratio is the number the resident
    serve loop exists to shrink — the per-token tax the host charges no
    matter how fast the device program is. ``SERVE_r14.json`` records
    the before/after; 0.0 until the engine has served anything."""
    reg = registry if registry is not None else get_registry()
    toks = reg.counter("serve.engine.tokens").value
    if not toks:
        return 0.0
    return reg.timer("serve.engine.host_sec").total / toks


# --------------------------------------------------------------------------
# FLOPs model (moved from bench.py so train + serve share one MFU basis)
# --------------------------------------------------------------------------

def train_flops_per_token(cfg, checkpoint: str, chunks: int):
    """(required, hardware) FLOPs per trained token.

    MAC counting: per layer, QKV+out projections 4*d^2 and FFN 2*d*d_ff; the
    attention score/value matmuls add seq*d per token (causal halves the
    window); the decoder projection d*vocab. One MAC = 2 FLOPs; backward
    costs 2x forward. ``required`` is the standard MFU numerator (3x forward,
    no recompute); ``hardware`` adds the remat re-forward the executor
    actually runs — the schedule-table executor applies the EXACT
    per-micro-batch policy (reference ``pipe.py:354``): except_last remats
    chunks-1 of chunks micro-batches. Only the per-layer term remats: the
    policy wraps the stage body, not embed/decoder.

    ``cfg`` is duck-typed (``d_model``/``d_ff``/``n_layers``/``vocab``/
    ``seq_len``/``causal``) so obs does not import the model zoo.
    """
    d, ff, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab
    eff_s = cfg.seq_len / 2 if cfg.causal else cfg.seq_len
    layer_macs = L * (4 * d * d + 2 * d * ff + 2 * eff_s * d)
    macs = layer_macs + d * V
    remat = {"never": 0.0, "except_last": (chunks - 1) / chunks,
             "always": 1.0}[checkpoint]
    required = 2 * macs * 3
    hardware = required + 2 * layer_macs * remat
    return required, hardware


# bf16 peak FLOP/s per chip by device kind (dense; conservative defaults).
_PEAK_BF16 = (
    ("v6", 918e12),     # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),  # device_kind "TPU v5 lite" (v5e)
    ("v5lite", 197e12),
    ("v4", 275e12),
)


def peak_flops_per_chip() -> float:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for tag, peak in _PEAK_BF16:
        if tag in kind:
            return peak
    return 197e12  # unknown kind: assume v5e-class


def device_memory_peaks() -> Dict[str, Dict[str, int]]:
    """Per-device ``memory_stats()`` peaks ({} per device on backends that
    do not report, e.g. the virtual CPU platform)."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    for dev in jax.local_devices():
        stats = getattr(dev, "memory_stats", lambda: None)() or {}
        out[str(dev)] = {k: stats[k] for k in
                         ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                         if k in stats}
    return out


# --------------------------------------------------------------------------
# StepReport
# --------------------------------------------------------------------------

@dataclasses.dataclass
class StepReport:
    """One step's telemetry, folded to the committed BENCH_*.json fields.

    ``compute`` derives throughput and MFU/HFU from raw timings;
    ``to_json`` emits the artifact-schema dict (``metric``/``value``/
    ``unit`` head keys, then the context fields every round carries).
    """

    step: int
    wall_sec: float
    tokens: int
    n_stages: int = 1
    chunks: int = 1
    checkpoint: str = "never"
    schedule: Optional[str] = None
    loss: Optional[float] = None
    tokens_per_sec: float = 0.0
    tokens_per_sec_per_chip: float = 0.0
    mfu: Optional[float] = None
    hfu: Optional[float] = None
    analytic_bubble: Optional[float] = None
    measured_bubble: Optional[float] = None
    measured_bubble_method: Optional[str] = None
    memory: Dict[str, Dict[str, int]] = dataclasses.field(default_factory=dict)
    compile_inclusive: bool = False
    platform: Optional[str] = None
    device_kind: Optional[str] = None
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @classmethod
    def compute(cls, *, step: int, wall_sec: float, tokens: int,
                n_stages: int = 1, chunks: int = 1,
                checkpoint: str = "never", schedule: Optional[str] = None,
                loss: Optional[float] = None, model_cfg=None,
                analytic_bubble: Optional[float] = None,
                measured_bubble: Optional[float] = None,
                measured_bubble_method: Optional[str] = None,
                memory: Optional[Dict[str, Dict[str, int]]] = None,
                compile_inclusive: bool = False,
                peak_flops: Optional[float] = None,
                platform: Optional[str] = None,
                device_kind: Optional[str] = None,
                **extra: Any) -> "StepReport":
        """Fold raw timings into derived rates. ``model_cfg`` (an LMConfig-
        shaped object) enables MFU/HFU via :func:`train_flops_per_token`;
        ``peak_flops`` overrides :func:`peak_flops_per_chip` (pass it to
        avoid a device lookup, e.g. in synthetic tests)."""
        tps = tokens / wall_sec if wall_sec > 0 else 0.0
        mfu = hfu = None
        if model_cfg is not None and wall_sec > 0:
            req_tok, hw_tok = train_flops_per_token(model_cfg, checkpoint,
                                                    chunks)
            peak = peak_flops if peak_flops is not None \
                else peak_flops_per_chip()
            per_chip = tps / max(n_stages, 1)
            mfu = (req_tok * per_chip) / peak
            hfu = (hw_tok * per_chip) / peak
        return cls(step=step, wall_sec=wall_sec, tokens=tokens,
                   n_stages=n_stages, chunks=chunks, checkpoint=checkpoint,
                   schedule=schedule, loss=loss, tokens_per_sec=tps,
                   tokens_per_sec_per_chip=tps / max(n_stages, 1),
                   mfu=mfu, hfu=hfu, analytic_bubble=analytic_bubble,
                   measured_bubble=measured_bubble,
                   measured_bubble_method=measured_bubble_method,
                   memory=memory or {}, compile_inclusive=compile_inclusive,
                   platform=platform, device_kind=device_kind, extra=extra)

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "metric": "train_tokens_per_sec_per_chip",
            "value": round(self.tokens_per_sec_per_chip, 2),
            "unit": "tokens/s/chip",
            "step": self.step,
            "wall_sec": round(self.wall_sec, 6),
            "tokens": self.tokens,
            "tokens_per_sec": round(self.tokens_per_sec, 2),
            "n_stages": self.n_stages,
            "chunks": self.chunks,
            "checkpoint": self.checkpoint,
            "schedule": self.schedule,
            "mfu": round(self.mfu, 4) if self.mfu is not None else None,
            "hfu": round(self.hfu, 4) if self.hfu is not None else None,
            "analytic_bubble": (round(self.analytic_bubble, 4)
                                if self.analytic_bubble is not None else None),
            "measured_bubble": (round(self.measured_bubble, 4)
                                if self.measured_bubble is not None else None),
            "measured_bubble_method": self.measured_bubble_method,
            "final_loss": (round(self.loss, 4)
                           if self.loss is not None else None),
            "memory": self.memory,
            "compile_inclusive": self.compile_inclusive,
            "platform": self.platform,
            "device_kind": self.device_kind,
        }
        out.update(self.extra)
        return out

    def scalar_items(self) -> List[Tuple[str, float]]:
        """(tag, value) pairs for a ``ScalarWriter`` sink."""
        items: List[Tuple[str, float]] = [
            ("telemetry/tokens_per_sec", self.tokens_per_sec),
            ("telemetry/ms_step", self.wall_sec * 1e3),
        ]
        if self.loss is not None:
            items.append(("telemetry/loss", self.loss))
        if self.mfu is not None:
            items.append(("telemetry/mfu", self.mfu))
        if self.hfu is not None:
            items.append(("telemetry/hfu", self.hfu))
        if self.analytic_bubble is not None:
            items.append(("telemetry/analytic_bubble", self.analytic_bubble))
        if self.measured_bubble is not None:
            items.append(("telemetry/measured_bubble", self.measured_bubble))
        for dev, stats in self.memory.items():
            if "peak_bytes_in_use" in stats:
                items.append((f"telemetry/peak_gib/{dev}",
                              stats["peak_bytes_in_use"] / 2 ** 30))
        return items
