"""Self-contained TensorBoard scalar writer (SURVEY §5: "stdout +
TensorBoard scalars").

The reference ecosystem logs scalars through torch's SummaryWriter; this
framework keeps its observability stack dependency-free (the profiler trace
side already emits Perfetto/TB traces via ``jax.profiler``), so the event
file format is implemented directly: a TFRecord stream of binary-encoded
``Event`` protos —

* record framing: ``[len u64le][masked_crc32c(len) u32le][payload]
  [masked_crc32c(payload) u32le]``, CRC32C (Castagnoli) with TensorBoard's
  rotate-and-add mask;
* ``Event`` proto fields used: ``wall_time`` (1, double), ``step``
  (2, varint), ``file_version`` (3, string — first record,
  ``"brain.Event:2"``), ``summary`` (5) → repeated ``Summary.Value``
  (1) → ``tag`` (1, string) + ``simple_value`` (2, float).

``tests/test_tb.py`` round-trips files through tensorboard's own
``EventAccumulator`` to pin format correctness.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import IO, Optional

__all__ = ["ScalarWriter"]

# --- CRC32C (Castagnoli), table-driven ------------------------------------

_CRC_TABLE = []


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE:
        return _CRC_TABLE
    poly = 0x82F63B78  # reflected Castagnoli
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    _CRC_TABLE = table
    return table


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --- minimal proto encoding ------------------------------------------------

def _varint(n: int) -> bytes:
    if n < 0:
        # proto varints encode negative int64 as 10-byte two's complement;
        # Python's arithmetic shift would otherwise never reach 0
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float32(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _scalar_event(tag: str, value: float, step: int,
                  wall_time: float) -> bytes:
    value_msg = (_len_delim(1, tag.encode("utf-8"))  # Summary.Value.tag
                 + _float32(2, value))               # .simple_value
    summary = _len_delim(1, value_msg)               # Summary.value
    return (_double(1, wall_time)                    # Event.wall_time
            + _key(2, 0) + _varint(step)             # Event.step
            + _len_delim(5, summary))                # Event.summary


def _version_event(wall_time: float) -> bytes:
    return _double(1, wall_time) + _len_delim(3, b"brain.Event:2")


class ScalarWriter:
    """Append-only scalar event writer for one run directory.

    >>> w = ScalarWriter("/tmp/run0")
    >>> w.add_scalar("train/loss", 3.14, step=10)
    >>> w.close()
    """

    def __init__(self, logdir: str):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        ts = time.time()
        host = socket.gethostname() or "host"
        self.path = os.path.join(
            logdir, f"events.out.tfevents.{int(ts)}.{host}")
        self._f: Optional[IO[bytes]] = open(self.path, "ab")
        self._write_record(_version_event(ts))
        self.flush()

    def _write_record(self, payload: bytes) -> None:
        if self._f is None:
            raise ValueError("writer is closed")
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: Optional[float] = None) -> None:
        self._write_record(
            _scalar_event(tag, float(value), int(step),
                          time.time() if wall_time is None else wall_time))

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None

    def __enter__(self) -> "ScalarWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
