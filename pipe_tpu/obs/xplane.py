"""Minimal XSpace (xplane.pb) reader — no proto toolchain required.

``jax.profiler`` traces serialize as XSpace protos; reading them back
normally needs ``jax.profiler.ProfileData`` (absent on older jax) or the
tensorflow/tensorboard proto stack (absent here by design — the repo's
observability layer is dependency-free, see ``tb_writer.py`` which hand-
ENCODES the TB event protos). This module is the decoding mirror: a wire-
format parser for exactly the XSpace fields the timeline tools read —

* ``XSpace.planes`` (1) → ``XPlane``: ``name`` (2), ``lines`` (3),
  ``event_metadata`` (4, map<int64, XEventMetadata>);
* ``XLine``: ``name`` (2), ``timestamp_ns`` (3), ``events`` (4);
* ``XEvent``: ``metadata_id`` (1), ``offset_ps`` (2), ``duration_ps`` (3);
* ``XEventMetadata``: ``id`` (1), ``name`` (2), ``display_name`` (4).

Event start times are absolute nanoseconds (``line.timestamp_ns +
offset_ps/1000``), matching ``ProfileData``'s ``start_ns`` convention, so
:mod:`.meters` and ``tools/timeline_report.py`` see one interface on every
jax version.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterator, List, Tuple

__all__ = ["TraceEvent", "TraceLine", "TracePlane", "parse_xspace",
           "load_trace_planes", "encode_xspace"]


@dataclasses.dataclass
class TraceEvent:
    name: str
    start_ns: float
    duration_ns: float

    @property
    def end_ns(self) -> float:
        return self.start_ns + self.duration_ns


@dataclasses.dataclass
class TraceLine:
    name: str
    timestamp_ns: int
    events: List[TraceEvent]


@dataclasses.dataclass
class TracePlane:
    name: str
    lines: List[TraceLine]


# --- protobuf wire-format primitives ---------------------------------------

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    """Yield ``(field_number, wire_type, payload)`` triples; varint payloads
    arrive pre-decoded as ints re-encoded positionally (returned raw int)."""
    pos, n = 0, len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 0x7
        if wire == 0:                       # varint
            val, pos = _read_varint(buf, pos)
            yield field, wire, val
        elif wire == 1:                     # fixed64
            yield field, wire, buf[pos:pos + 8]
            pos += 8
        elif wire == 2:                     # length-delimited
            ln, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos:pos + ln]
            pos += ln
        elif wire == 5:                     # fixed32
            yield field, wire, buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire} at {pos}")


def _parse_event(buf: bytes) -> Tuple[int, int, int]:
    metadata_id = offset_ps = duration_ps = 0
    for field, wire, val in _fields(buf):
        if field == 1 and wire == 0:
            metadata_id = val
        elif field == 2 and wire == 0:
            offset_ps = val
        elif field == 3 and wire == 0:
            duration_ps = val
    return metadata_id, offset_ps, duration_ps


def _parse_line(buf: bytes) -> Tuple[str, int, List[Tuple[int, int, int]]]:
    name, timestamp_ns, events = "", 0, []
    for field, wire, val in _fields(buf):
        if field == 2 and wire == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3 and wire == 0:
            timestamp_ns = val
        elif field == 4 and wire == 2:
            events.append(_parse_event(val))
    return name, timestamp_ns, events


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    mid, name, display = 0, "", ""
    for field, wire, val in _fields(buf):
        if field == 1 and wire == 0:
            mid = val
        elif field == 2 and wire == 2:
            name = val.decode("utf-8", "replace")
        elif field == 4 and wire == 2:
            display = val.decode("utf-8", "replace")
    return mid, display or name


def _parse_metadata_entry(buf: bytes) -> Tuple[int, str]:
    """One map<int64, XEventMetadata> entry (key=1, value=2)."""
    key, name = 0, ""
    for field, wire, val in _fields(buf):
        if field == 1 and wire == 0:
            key = val
        elif field == 2 and wire == 2:
            mid, name = _parse_event_metadata(val)
            key = key or mid
    return key, name


def _parse_plane(buf: bytes) -> TracePlane:
    name = ""
    raw_lines: List[Tuple[str, int, List[Tuple[int, int, int]]]] = []
    metadata: Dict[int, str] = {}
    for field, wire, val in _fields(buf):
        if field == 2 and wire == 2:
            name = val.decode("utf-8", "replace")
        elif field == 3 and wire == 2:
            raw_lines.append(_parse_line(val))
        elif field == 4 and wire == 2:
            key, mname = _parse_metadata_entry(val)
            metadata[key] = mname
    lines = []
    for lname, ts, raw_events in raw_lines:
        events = [TraceEvent(name=metadata.get(mid, f"metadata:{mid}"),
                             start_ns=ts + off_ps / 1e3,
                             duration_ns=dur_ps / 1e3)
                  for mid, off_ps, dur_ps in raw_events]
        lines.append(TraceLine(name=lname, timestamp_ns=ts, events=events))
    return TracePlane(name=name, lines=lines)


def parse_xspace(data: bytes) -> List[TracePlane]:
    """Parse one serialized XSpace into its planes."""
    return [_parse_plane(val) for field, wire, val in _fields(data)
            if field == 1 and wire == 2]


# --- encoder (synthetic traces for tests and offline fixtures) -------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field(num: int, wire: int, payload: bytes) -> bytes:
    return _varint(num << 3 | wire) + payload


def _msg(num: int, payload: bytes) -> bytes:
    return _field(num, 2, _varint(len(payload)) + payload)


def encode_xspace(planes: List[TracePlane]) -> bytes:
    """Serialize planes back to XSpace wire format (inverse of
    :func:`parse_xspace`, same field subset). Lets tests and fixtures
    fabricate device planes without a real TPU capture."""
    out = bytearray()
    for plane in planes:
        names = {}
        for line in plane.lines:
            for ev in line.events:
                names.setdefault(ev.name, len(names) + 1)
        pbuf = bytearray(_msg(2, plane.name.encode()))
        for line in plane.lines:
            lbuf = bytearray(_msg(2, line.name.encode()))
            lbuf += _field(3, 0, _varint(line.timestamp_ns))
            for ev in line.events:
                ebuf = (_field(1, 0, _varint(names[ev.name]))
                        + _field(2, 0, _varint(
                            int((ev.start_ns - line.timestamp_ns) * 1e3)))
                        + _field(3, 0, _varint(int(ev.duration_ns * 1e3))))
                lbuf += _msg(4, bytes(ebuf))
            pbuf += _msg(3, bytes(lbuf))
        for name, mid in names.items():
            meta = _field(1, 0, _varint(mid)) + _msg(2, name.encode())
            entry = _field(1, 0, _varint(mid)) + _msg(2, meta)
            pbuf += _msg(4, entry)
        out += _msg(1, bytes(pbuf))
    return bytes(out)


def load_trace_planes(logdir: str) -> List[TracePlane]:
    """All planes from every ``*.xplane.pb`` under a ``profile_trace``
    capture directory (one file per host per session)."""
    planes: List[TracePlane] = []
    for root, _, files in os.walk(logdir):
        for fname in sorted(files):
            if fname.endswith(".xplane.pb"):
                with open(os.path.join(root, fname), "rb") as f:
                    planes.extend(parse_xspace(f.read()))
    return planes
