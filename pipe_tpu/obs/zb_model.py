"""Roofline-style cost model for zb-h1 vs 1F1B — the falsifiable win criterion.

Why this exists (VERDICT r3 #4): zb-h1's table idle fraction is ~2.4x lower
than 1F1B's, yet every wall-clock measurement ever taken of it — on the
serialized 8-virtual-device CPU mesh, the only hardware available here — runs
~1.6x SLOWER. Both facts are real; they are statements about different
machines. This module turns the schedule tables plus calibrated per-op costs
into predictions for both machines, so the cpu8 measurement can VALIDATE the
model and the model can then predict the real-hardware crossover instead of
the docs hand-waving from idle fractions.

The model
---------

Per-op costs, in units of one stage forward ``f``:

* ``FWD`` = ``f``;
* ``BWD`` (combined input+weight grads) = ``2 f`` (two transposed matmul
  families per forward matmul — the standard 2x);
* zb-h1's split backward: B (input-grad) + W (weight-grad) each
  ``sigma * f`` where ``sigma`` is the measured SPLIT OVERHEAD factor —
  ideally 1.0, in practice > 1: the split stores full residuals, parks
  cotangents/taps through slot stores, and (structural split) re-reads
  taps. The committed cpu8 calibration (``ZB_CROSSOVER_r04.json``)
  measures sigma 1.90 (d_model 64) to 2.33 (d_model 128) — sigma is
  WIDTH-DEPENDENT (slot-store traffic scales differently than compute),
  which is why the committed gate is the per-config breakeven sigma*,
  not one pooled number;
* ``IDLE`` = 0;
* plus a per-cycle machinery overhead ``o`` (table indexing, ppermute
  launch, conditional-copy traffic) paid once per cycle regardless of ops.

Two execution modes:

* ``serialized`` (the cpu8 test platform): one core executes every virtual
  device in turn — wall = sum of ALL op costs + cycles * o. Idle slots are
  nearly free, so schedules with more total work (zb's sigma) lose even when
  their tables are denser. This mode is CHECKED against measurement.
* ``parallel`` (real multi-chip): devices run concurrently — wall = sum over
  cycles of the MAX per-device op cost in that cycle + cycles * o. Idle
  slots burn real time here, which is the entire point of zero-bubble.

Calibration: :func:`calibrate` solves for ``(f_width..., sigma, o)`` from
1f1b+zb-h1 serialized measurements at >= 2 widths (f scales with width;
sigma, o do not). :func:`predict` then evaluates both modes;
:func:`crossover` reports, per (m, n), the largest per-cycle overhead
``o_hw`` (in f units) at which zb-h1 still beats 1F1B on parallel hardware —
``o_max <= 0`` means zb-h1 is predicted to lose there outright.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np

from ..core.schedule import BWD, FWD, IDLE, WGRAD, get_schedule

__all__ = ["OpCosts", "schedule_wall", "calibrate", "fitted_op_costs",
           "predict", "crossover", "analytic_bubbles"]


def analytic_bubbles(m: int, n: int,
                     names: Sequence[str] = ("1f1b", "zb-h1", "zb-h2"),
                     ) -> Dict[str, float]:
    """Analytic idle fractions of the named schedules' op tables at
    (m, n), per-op-slot (a 1F1B combined backward occupies ONE slot worth
    two units of work — the same accounting every ``Schedule.bubble``
    uses, so the numbers are cross-comparable). The split tables' W ops
    count as real work: this is the table-density claim the zero-bubble
    schedules make, and ``test_zb_model`` pins zb-h1/zb-h2 strictly below
    1f1b here."""
    return {name: float(get_schedule(name).bubble(m, n)) for name in names}


@dataclasses.dataclass(frozen=True)
class OpCosts:
    """Per-op costs in seconds. ``b`` covers the combined backward; split
    tables (zb-h1) price their B and W ops at ``sigma * b / 2`` each."""

    f: float
    sigma: float = 1.0
    o: float = 0.0

    @property
    def b(self) -> float:
        return 2.0 * self.f

    def of(self, op: int, split_table: bool) -> float:
        if op == FWD:
            return self.f
        if op == BWD:
            return self.sigma * self.b / 2.0 if split_table else self.b
        if op == WGRAD:
            return self.sigma * self.b / 2.0
        return 0.0


def _cost_table(op: np.ndarray, costs: OpCosts) -> np.ndarray:
    split_table = bool((op == WGRAD).any())
    out = np.zeros(op.shape, np.float64)
    for v in (FWD, BWD, WGRAD):
        out[op == v] = costs.of(v, split_table)
    return out


def schedule_wall(op: np.ndarray, costs: OpCosts, mode: str) -> float:
    """Predicted wall seconds of one step of an op table under ``costs``."""
    ct = _cost_table(op, costs)
    T = op.shape[0]
    if mode == "parallel":
        return float(ct.max(axis=1).sum() + T * costs.o)
    if mode == "serialized":
        return float(ct.sum() + T * costs.o)
    raise ValueError(f"mode must be parallel|serialized, got {mode!r}")


def _op_counts(name: str, m: int, n: int):
    op = get_schedule(name).op_tables(m, n)[0]
    return op, op.shape[0]


def calibrate(measurements: Sequence[dict], n: int) -> dict:
    """Fit ``(f_per_width, sigma, o)`` from serialized (cpu8) measurements.

    ``measurements``: one dict per (width, m) point:
    ``{"width": int, "m": int, "t_1f1b": sec, "t_zb": sec}``.
    Least-squares over the linear system (per width ``w``, micro-batch
    count ``m``):

    * ``t_1f1b(w, m) = (F + 2 B) f_w + C_1f1b(m) o``
    * ``t_zb(w, m)   = F f_w + (B + W) s_w + C_zb(m) o``

    with ``s_w = sigma * f_w`` recovered as the per-width ratio. At least
    TWO distinct ``m`` values per width are required — op counts scale
    with m while the fill/drain cycle surplus does not, which is what
    separates ``o`` from the op costs and overdetermines the system (a
    single m per width leaves 2k equations for 2k+1 unknowns and the
    residual is vacuously zero). Large sigma spread across widths
    falsifies the constant-sigma assumption; a large ``rel_residual``
    falsifies the cost model itself.
    """
    widths = sorted({ms["width"] for ms in measurements})
    for w in widths:
        if len({ms["m"] for ms in measurements if ms["width"] == w}) < 2:
            raise ValueError(
                f"calibrate needs >= 2 distinct micro-batch counts PER "
                f"width (width {w} has fewer): each width fits "
                "independently, and one m leaves its system "
                "underdetermined (o unidentifiable, residual vacuously 0)")
    # Fit each width INDEPENDENTLY (f_w, s_w, o_w): the per-cycle overhead
    # includes ring ppermutes of width-sized buffers, so a width-shared o
    # is mis-specified (tried; it drives f negative on real timings).
    f_w, s_w, o_w, sigmas, resids = [], [], [], [], []
    for w in widths:
        rows = [ms for ms in measurements if ms["width"] == w]
        A = np.zeros((2 * len(rows), 3))
        y = np.zeros(2 * len(rows))
        for r, ms in enumerate(rows):
            m = ms["m"]
            op1, C1 = _op_counts("1f1b", m, n)
            opz, Cz = _op_counts("zb-h1", m, n)
            F1 = int((op1 == FWD).sum())
            B1 = int((op1 == BWD).sum())
            Fz = int((opz == FWD).sum())
            Bz = int((opz == BWD).sum())
            Wz = int((opz == WGRAD).sum())
            A[2 * r] = [F1 + 2 * B1, 0.0, C1]
            y[2 * r] = ms["t_1f1b"]
            A[2 * r + 1] = [Fz, Bz + Wz, Cz]
            y[2 * r + 1] = ms["t_zb"]
        sol, *_ = np.linalg.lstsq(A, y, rcond=None)
        f, s, o = (float(v) for v in sol)
        f_w.append(f)
        s_w.append(s)
        o_w.append(o)
        # OpCosts prices each split op at sigma * f, so sigma = s / f
        sigmas.append(s / f if f > 0 else float("nan"))
        resid = A @ sol - y
        resids.append(float(np.linalg.norm(resid)
                            / max(np.linalg.norm(y), 1e-12)))
    # pooled sigma: weighted by f (larger widths dominate, least noisy)
    good = [(f, sg) for f, sg in zip(f_w, sigmas)
            if f > 0 and np.isfinite(sg)]
    sigma = (float(np.average([sg for _, sg in good],
                              weights=[f for f, _ in good]))
             if good else float("nan"))
    return {
        "n": n,
        "widths": widths,
        "ms": sorted({ms["m"] for ms in measurements}),
        "f_per_width": f_w,
        "sigma_per_width": sigmas,
        "sigma": sigma,
        "o_serialized_per_width": o_w,
        "rel_residual_per_width": resids,
        # The fitted OpCosts per width, JSON-shaped, RIGHT NEXT TO the
        # residual that says whether to believe them: consumers ranking
        # schedules on this fit (core/planner.py) must check
        # `rel_residual` first — a large value falsifies the linear cost
        # model itself, and every prediction built on it.
        "op_costs_per_width": [
            {"f": f, "sigma": sg, "o": o}
            for f, sg, o in zip(f_w, sigmas, o_w)],
        "rel_residual": (max(resids) if resids else float("nan")),
    }


def fitted_op_costs(calib: dict, width: Optional[int] = None) -> OpCosts:
    """The :class:`OpCosts` a :func:`calibrate` fit implies for ``width``
    (default: the largest width with a physical ``f > 0`` — wider layers
    dominate real models and give the least-noisy fit). Raises
    ``ValueError`` when no width produced a physical fit."""
    if width is not None:
        k = calib["widths"].index(width)
        row = calib["op_costs_per_width"][k]
        return OpCosts(f=row["f"], sigma=row["sigma"], o=row["o"])
    good = [k for k, f in enumerate(calib["f_per_width"]) if f > 0]
    if not good:
        raise ValueError(
            "calibration produced no physical fit (every width has f <= 0 "
            "— the linear cost model was violated, e.g. cache spill)")
    row = calib["op_costs_per_width"][good[-1]]
    return OpCosts(f=row["f"], sigma=row["sigma"], o=row["o"])


def predict(m: int, n: int, costs: OpCosts, mode: str,
            zb: str = "zb-h1") -> dict:
    """Wall-clock predictions for 1f1b and a zb table under one cost
    model (``zb`` picks the split schedule: zb-h1 or zb-h2)."""
    t1 = schedule_wall(_op_counts("1f1b", m, n)[0], costs, mode)
    tz = schedule_wall(_op_counts(zb, m, n)[0], costs, mode)
    return {"mode": mode, "m": m, "n": n, "zb": zb,
            "t_1f1b": t1, "t_zb": tz,
            "zb_over_1f1b": tz / t1 if t1 > 0 else float("nan"),
            "zb_wins": tz < t1}


def crossover(m: int, n: int, sigma: float,
              f: float = 1.0) -> dict:
    """The falsifiable criterion: on PARALLEL hardware, the largest
    per-cycle overhead ``o_max`` (in units of ``f``) at which zb-h1 still
    beats 1F1B at this (m, n, sigma). Derivation: wall difference is
    linear in ``o`` with slope ``C_zb - C_1f1b`` (zb tables have more
    cycles), so ``o_max = (wall_1f1b(o=0) - wall_zb(o=0)) / (C_zb -
    C_1f1b)``. ``o_max <= 0``: zb-h1 predicted to LOSE outright (the
    sigma work overhead exceeds the bubble win)."""
    c0 = OpCosts(f=f, sigma=sigma, o=0.0)
    op1, C1 = _op_counts("1f1b", m, n)
    opz, Cz = _op_counts("zb-h1", m, n)
    t1 = schedule_wall(op1, c0, "parallel")
    tz = schedule_wall(opz, c0, "parallel")
    dC = Cz - C1
    if dC <= 0:
        o_max = float("inf") if tz < t1 else float("-inf")
    else:
        o_max = (t1 - tz) / dC
    # Breakeven split overhead sigma* (at o=0): zb-h1's parallel wall is
    # linear in sigma for sigma >= 1 — a cycle containing any B/W op
    # costs sigma*f (its max), an F-only cycle costs f. zb wins iff
    # sigma < sigma*. This is THE falsifiable gate: measure sigma on the
    # target hardware, compare against sigma*(m, n).
    has_bw = ((opz == BWD) | (opz == WGRAD)).any(axis=1)
    has_f = (opz == FWD).any(axis=1)
    n_bw_cycles = int(has_bw.sum())
    n_f_only = int((has_f & ~has_bw).sum())
    sigma_star = ((t1 / f - n_f_only) / n_bw_cycles
                  if n_bw_cycles else float("inf"))
    return {"m": m, "n": n, "sigma": sigma,
            "cycles_1f1b": C1, "cycles_zb": Cz,
            "t_1f1b_o0": t1 / f, "t_zb_o0": tz / f,
            "zb_wins_at_o0": tz < t1,
            "o_max_f_units": o_max / f,
            "breakeven_sigma": max(sigma_star, 0.0)}
