"""Profiling and observability utilities.

Capability parity with the reference's two tracing mechanisms (SURVEY §5):

* kernel-level spans ``record_function("chunk%d-part%d")`` around every task
  (reference ``pipeline.py:205-210``, removed by the local edit but
  documented at ``README.md:263,408``) → :func:`stage_scope` emits
  ``jax.named_scope("chunk{i}-stage{j}")``, which survives into XLA HLO op
  names and Perfetto traces (the emulator already wraps every task in it);
* driver-level ``torch.profiler`` with TensorBoard handler
  (``main.py:196-204``) → :func:`profile_trace` wraps ``jax.profiler``;
* CUDA memory-history snapshots (``main.py:263-271``) →
  :func:`device_memory_report` via ``jax.profiler.device_memory_profile``;
* the BASELINE.md north-star pipeline-bubble %% → :class:`BubbleMeter`
  (analytic model now; per-stage idle extraction from traces is the
  measured upgrade, SURVEY §7 hard part #4).
"""

from __future__ import annotations

import contextlib
import dataclasses
import re
from typing import Dict, List, Optional, Tuple

import jax

from ..core.schedule import Schedule, bubble_fraction
from .xplane import load_trace_planes

__all__ = ["stage_scope", "profile_trace", "device_memory_report",
           "BubbleMeter", "stage_busy_from_trace",
           "stage_timeline_from_trace", "measured_bubble_slope",
           "measured_bubble_two_point"]


def stage_scope(microbatch: int, stage: int):
    """Named scope attributing ops to (micro-batch, stage) in traces."""
    return jax.named_scope(f"chunk{microbatch}-stage{stage}")


@contextlib.contextmanager
def profile_trace(logdir: str, *, host_tracer_level: int = 2):
    """Capture a profiler trace viewable in TensorBoard/Perfetto/XProf.

    ``ProfileOptions`` is a recent jax addition; older releases take no
    options and trace at their default host level — same capture files.
    """
    try:
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=options)
    except AttributeError:
        jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def device_memory_report(device: Optional[jax.Device] = None) -> str:
    """Human-readable live-buffer summary (pprof textproto under the hood)."""
    import gzip

    device = device or jax.devices()[0]
    raw = jax.profiler.device_memory_profile()
    try:
        raw = gzip.decompress(raw)
    except OSError:
        pass
    lines = [f"device memory profile ({device}):",
             f"  raw pprof bytes: {len(raw)}"]
    stats = getattr(device, "memory_stats", lambda: None)()
    if stats:
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in stats:
                lines.append(f"  {k}: {stats[k] / 2**30:.3f} GiB")
    return "\n".join(lines)


@dataclasses.dataclass
class BubbleMeter:
    """Pipeline-bubble accounting for a (chunks m, stages n) configuration.

    ``analytic`` is the fill–drain model (n-1)/(m+n-1) (reference
    ``_clock_cycles`` cost model, ``pipeline.py:63-79``); ``measured`` can be
    filled from per-stage busy times (e.g. extracted from a profiler trace)
    to report the honest number next to the model.
    """

    chunks: int
    n_stages: int
    schedule: Optional[Schedule] = None

    @property
    def analytic(self) -> float:
        if self.schedule is not None:
            return self.schedule.bubble(self.chunks, self.n_stages)
        return bubble_fraction(self.chunks, self.n_stages)

    def measured(self, stage_busy_sec, wall_sec: float) -> float:
        """1 - busy/total from per-stage busy seconds and the step wall time."""
        total = self.n_stages * wall_sec
        busy = float(sum(stage_busy_sec))
        return max(0.0, 1.0 - busy / total) if total > 0 else 0.0

    def report(self) -> str:
        return (f"bubble[m={self.chunks}, n={self.n_stages}] "
                f"analytic={self.analytic:.2%}")


def _merge_intervals(events: List[Tuple[float, float]]
                     ) -> List[Tuple[float, float]]:
    """Union of [start, end) intervals (events overlap across lines)."""
    events = sorted(events)
    merged: List[Tuple[float, float]] = []
    for s, e in events:
        if merged and s <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1] = (merged[-1][0], e)
        else:
            merged.append((s, e))
    return merged


def _merge_busy_ns(events: List[Tuple[float, float]]) -> float:
    """Union length of [start, end) intervals."""
    return sum(e - s for s, e in _merge_intervals(events))


def stage_busy_from_trace(logdir: str) -> Dict[str, float]:
    """Per-device busy seconds from a :func:`profile_trace` capture.

    Parses the xplane protos (dependency-free, any jax version — see
    :mod:`.xplane`) and merges the op-event intervals of every
    ``/device:*`` plane — the trace-driven counterpart of the reference
    author's TensorBoard-trace verification
    (``/root/reference/README.md:559-567``). Returns ``{plane_name:
    busy_sec}`` plus a ``"_span"`` key holding the whole trace's wall span
    in seconds. Device planes exist for real accelerators
    (``/device:TPU:0`` ...); the virtual CPU platform reports only host
    threads, for which :func:`measured_bubble_slope` is the fallback.
    """
    busy: Dict[str, float] = {}
    lo, hi = float("inf"), 0.0
    for plane in load_trace_planes(logdir):
        if not plane.name.startswith("/device:"):
            continue
        events: List[Tuple[float, float]] = []
        for line in plane.lines:
            for ev in line.events:
                events.append((ev.start_ns, ev.end_ns))
                lo, hi = min(lo, ev.start_ns), max(hi, ev.end_ns)
        if events:
            busy[plane.name] = busy.get(plane.name, 0.0) + \
                _merge_busy_ns(events) / 1e9
    busy["_span"] = (hi - lo) / 1e9 if hi > lo else 0.0
    return busy


_SCOPE_RE = re.compile(r"chunk(\d+)-stage(\d+)")


def stage_timeline_from_trace(logdir: str) -> Dict[str, object]:
    """Per-stage busy/idle attribution bucketed by the ``chunk{i}-stage{j}``
    named scopes (:func:`stage_scope` — they survive into XLA op names).

    Extends :func:`stage_busy_from_trace` from per-plane to per-stage: every
    event whose name carries a scope tag is credited to that (stage,
    micro-batch) bucket, intervals unioned per bucket. Prefers ``/device:*``
    planes; when none exist (virtual CPU platform) it falls back to host
    planes carrying scope-tagged events, and reports which source it used so
    callers can label the numbers honestly.

    Returns::

        {"source": "device" | "host" | None,      # None: no tagged events
         "span": (lo_ns, hi_ns),                   # over tagged events
         "stages": {j: {"busy_sec": float,
                        "intervals": [(s_ns, e_ns), ...],   # merged
                        "chunks": {i: busy_sec}}}}
    """
    planes = load_trace_planes(logdir)
    for source, keep in (("device", lambda p: p.name.startswith("/device:")),
                         ("host", lambda p: True)):
        raw: Dict[int, List[Tuple[float, float]]] = {}
        per_chunk: Dict[int, Dict[int, float]] = {}
        lo, hi = float("inf"), 0.0
        for plane in planes:
            if not keep(plane):
                continue
            for line in plane.lines:
                for ev in line.events:
                    m = _SCOPE_RE.search(ev.name)
                    if not m:
                        continue
                    chunk, stage = int(m.group(1)), int(m.group(2))
                    raw.setdefault(stage, []).append((ev.start_ns, ev.end_ns))
                    ch = per_chunk.setdefault(stage, {})
                    ch[chunk] = ch.get(chunk, 0.0) + ev.duration_ns / 1e9
                    lo, hi = min(lo, ev.start_ns), max(hi, ev.end_ns)
        if raw:
            stages = {}
            for stage, events in sorted(raw.items()):
                merged = _merge_intervals(events)
                stages[stage] = {
                    "busy_sec": sum(e - s for s, e in merged) / 1e9,
                    "intervals": merged,
                    "chunks": dict(sorted(per_chunk[stage].items())),
                }
            return {"source": source, "span": (lo, hi), "stages": stages}
    return {"source": None, "span": (0.0, 0.0), "stages": {}}


def measured_bubble_slope(t_m: float, t_2m: float, m: int) -> float:
    """Measured bubble from two step timings at ``m`` and ``2m`` micro-batches.

    With per-micro-batch work held constant, a clock-cycle pipeline costs
    ``t(m) = c + a*(m + n - 1)``; the slope ``a = (t(2m) - t(m)) / m`` is the
    real per-cycle cost (compute + ppermute + scan machinery, as executed).
    The measured bubble is the step-time fraction not spent on the ``m``
    useful cycles::

        bubble = 1 - m*a / t(m)

    which reduces to the analytic ``(n-1)/(m+n-1)`` when per-cycle cost
    dominates, and additionally exposes constant dispatch/gather overhead
    (at n=1 the analytic model says 0; this reports the honest residue).
    Timing-based, so it works on any platform — the trace-based
    :func:`stage_busy_from_trace` + :meth:`BubbleMeter.measured` pair is the
    per-stage-attributed alternative on real device planes.
    """
    return measured_bubble_two_point(t_m, m, t_2m, 2 * m)


def measured_bubble_two_point(t_ref: float, m_ref: int,
                              t_other: float, m_other: int) -> float:
    """:func:`measured_bubble_slope` generalized to any two micro-batch
    counts: the bubble is reported at the REFERENCE point ``(t_ref,
    m_ref)``; the other point only fixes the slope. Lets the probe use
    FEWER micro-batches than the headline run (e.g. m/2 vs m) when a 2m
    program would not fit — the straight-line d=1 specialization's HLO temp
    footprint grows with the unroll length, so probing downward keeps the
    slope measurable at the memory ceiling.

    Caveat: the premise is that step time is affine in the micro-batch
    count. Fixed per-step costs that do NOT scale with m (optimizer update,
    remote-dispatch latency) bias the slope low and the bubble high — on a
    tunneled single chip the bias dominates, so prefer the trace-based
    busy fraction (:func:`stage_busy_from_trace`) whenever a real device
    plane is available."""
    if t_ref <= 0 or m_other == m_ref:
        return 0.0
    a = max((t_other - t_ref) / (m_other - m_ref), 0.0)
    return max(0.0, 1.0 - (m_ref * a) / t_ref)
