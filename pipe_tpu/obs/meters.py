"""Profiling and observability utilities.

Capability parity with the reference's two tracing mechanisms (SURVEY §5):

* kernel-level spans ``record_function("chunk%d-part%d")`` around every task
  (reference ``pipeline.py:205-210``, removed by the local edit but
  documented at ``README.md:263,408``) → :func:`stage_scope` emits
  ``jax.named_scope("chunk{i}-stage{j}")``, which survives into XLA HLO op
  names and Perfetto traces (the emulator already wraps every task in it);
* driver-level ``torch.profiler`` with TensorBoard handler
  (``main.py:196-204``) → :func:`profile_trace` wraps ``jax.profiler``;
* CUDA memory-history snapshots (``main.py:263-271``) →
  :func:`device_memory_report` via ``jax.profiler.device_memory_profile``;
* the BASELINE.md north-star pipeline-bubble %% → :class:`BubbleMeter`
  (analytic model now; per-stage idle extraction from traces is the
  measured upgrade, SURVEY §7 hard part #4).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional

import jax

from ..core.schedule import Schedule, bubble_fraction

__all__ = ["stage_scope", "profile_trace", "device_memory_report",
           "BubbleMeter"]


def stage_scope(microbatch: int, stage: int):
    """Named scope attributing ops to (micro-batch, stage) in traces."""
    return jax.named_scope(f"chunk{microbatch}-stage{stage}")


@contextlib.contextmanager
def profile_trace(logdir: str, *, host_tracer_level: int = 2):
    """Capture a profiler trace viewable in TensorBoard/Perfetto/XProf."""
    options = jax.profiler.ProfileOptions()
    options.host_tracer_level = host_tracer_level
    jax.profiler.start_trace(logdir, profiler_options=options)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def device_memory_report(device: Optional[jax.Device] = None) -> str:
    """Human-readable live-buffer summary (pprof textproto under the hood)."""
    import gzip

    device = device or jax.devices()[0]
    raw = jax.profiler.device_memory_profile()
    try:
        raw = gzip.decompress(raw)
    except OSError:
        pass
    lines = [f"device memory profile ({device}):",
             f"  raw pprof bytes: {len(raw)}"]
    stats = getattr(device, "memory_stats", lambda: None)()
    if stats:
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in stats:
                lines.append(f"  {k}: {stats[k] / 2**30:.3f} GiB")
    return "\n".join(lines)


@dataclasses.dataclass
class BubbleMeter:
    """Pipeline-bubble accounting for a (chunks m, stages n) configuration.

    ``analytic`` is the fill–drain model (n-1)/(m+n-1) (reference
    ``_clock_cycles`` cost model, ``pipeline.py:63-79``); ``measured`` can be
    filled from per-stage busy times (e.g. extracted from a profiler trace)
    to report the honest number next to the model.
    """

    chunks: int
    n_stages: int
    schedule: Optional[Schedule] = None

    @property
    def analytic(self) -> float:
        if self.schedule is not None:
            return self.schedule.bubble(self.chunks, self.n_stages)
        return bubble_fraction(self.chunks, self.n_stages)

    def measured(self, stage_busy_sec, wall_sec: float) -> float:
        """1 - busy/total from per-stage busy seconds and the step wall time."""
        total = self.n_stages * wall_sec
        busy = float(sum(stage_busy_sec))
        return max(0.0, 1.0 - busy / total) if total > 0 else 0.0

    def report(self) -> str:
        return (f"bubble[m={self.chunks}, n={self.n_stages}] "
                f"analytic={self.analytic:.2%}")
