"""Structured JSONL event log with nested spans.

The host-side counterpart of the trace scopes: where
``meters.stage_scope`` names *device* work for the profiler
(``chunk{i}-stage{j}`` in XLA op names), :class:`EventLog` records *host*
structure — steps, compiles, evaluation, serving calls, and (on the
emulator, which runs tasks in Python) per-stage/per-micro-batch task
spans — as one JSON object per line, cheap enough to leave on in
production loops.

Record schema (one dict per line)::

    {"kind": <str>, "id": <int>, "parent": <int|null>,
     "t": <sec since log open>, "dur": <sec, spans only>, ...attrs}

plus a ``log_open`` header carrying the wall-clock epoch so host events
can be correlated with profiler traces. Span kinds used by the built-in
wiring: ``step``, ``stage``, ``microbatch``, ``comm``,
``checkpoint-recompute``, ``request`` (:data:`SPAN_KINDS`);
``step_report`` records carry a full :class:`~.telemetry.StepReport`
(``to_json`` payload).

Spans nest through a per-thread stack: ``parent`` is the id of the
innermost open span on the same thread. Records are written at span
*exit*, so children precede parents in the file; :meth:`EventLog.read`
returns them in file order and tests reconstruct the tree from
``id``/``parent``.

``NULL_EVENT_LOG`` is the disabled sink — same API, no file, no clock
reads beyond the context-manager protocol — so call sites never branch.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, IO, List, Optional

__all__ = ["EventLog", "NullEventLog", "NULL_EVENT_LOG", "SPAN_KINDS",
           "STEP", "STAGE", "MICROBATCH", "COMM", "RECOMPUTE", "REQUEST",
           "RECOVERY"]

STEP = "step"
STAGE = "stage"
MICROBATCH = "microbatch"
COMM = "comm"
RECOMPUTE = "checkpoint-recompute"
# serving: one record per retired request, written by the serve engine at
# retirement (see docs/observability.md "Request spans" for the schema)
REQUEST = "request"
# resilience: instantaneous records (not spans) written at every rung of
# the recovery ladder — skip/rewind (action=...) and the elastic path
# (stage_lost, replan, buddy_restore) — so a post-mortem can replay the
# escalation from the event log alone
RECOVERY = "recovery"
SPAN_KINDS = (STEP, STAGE, MICROBATCH, COMM, RECOMPUTE, REQUEST)


class EventLog:
    """Append-only JSONL event sink with nested span support.

    ``max_bytes`` arms size-bounded rotation: once the live file would
    exceed it, the file is renamed to ``<path>.1`` (replacing any
    previous rollover — at most two files ever exist) and a fresh file
    opens with a ``log_open`` header carrying ``rotated=True``. Long
    fleet drills keep at most ``2 * max_bytes`` on disk. A reader that
    races a writer (or a crash mid-line) can leave a torn final line;
    :meth:`read` tolerates exactly that — a final line that does not
    parse is dropped, a torn line anywhere else still raises."""

    def __init__(self, path: str, *, autoflush: bool = True,
                 max_bytes: Optional[int] = None):
        if max_bytes is not None and max_bytes < 1024:
            raise ValueError(f"max_bytes must be >= 1024, got {max_bytes}")
        self.path = path
        self._autoflush = autoflush
        self._max_bytes = max_bytes
        self._file: Optional[IO[str]] = open(path, "a")
        self._written = self._file.tell()
        self._lock = threading.Lock()
        self._local = threading.local()
        self._next_id = 0
        self._t0 = time.perf_counter()
        self._write({"kind": "log_open", "wall_time": time.time(),
                     "id": self._alloc_id(), "parent": None, "t": 0.0})

    # -- plumbing ----------------------------------------------------------

    def _alloc_id(self) -> int:
        self._next_id += 1
        return self._next_id

    def _stack(self) -> List[int]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record)
        with self._lock:
            if self._file is None:
                return
            if self._max_bytes is not None \
                    and self._written + len(line) + 1 > self._max_bytes \
                    and self._written > 0:
                self._rotate_locked()
            self._file.write(line + "\n")
            self._written += len(line) + 1
            if self._autoflush:
                self._file.flush()

    def _rotate_locked(self) -> None:
        """Roll the live file to ``<path>.1`` (caller holds the lock)."""
        self._file.close()
        os.replace(self.path, self.path + ".1")
        self._file = open(self.path, "a")
        self._written = 0
        header = json.dumps({"kind": "log_open", "wall_time": time.time(),
                             "id": self._alloc_id(), "parent": None,
                             "t": time.perf_counter() - self._t0,
                             "rotated": True})
        self._file.write(header + "\n")
        self._written += len(header) + 1

    # -- recording ---------------------------------------------------------

    def event(self, kind: str, **attrs: Any) -> None:
        """Instantaneous event under the current span (if any)."""
        stack = self._stack()
        rec = {"kind": kind, "id": self._alloc_id(),
               "parent": stack[-1] if stack else None,
               "t": time.perf_counter() - self._t0}
        rec.update(attrs)
        self._write(rec)

    @contextlib.contextmanager
    def span(self, kind: str, **attrs: Any):
        """Timed span; nests under the innermost open span on this thread."""
        stack = self._stack()
        span_id = self._alloc_id()
        parent = stack[-1] if stack else None
        stack.append(span_id)
        t0 = time.perf_counter()
        try:
            yield span_id
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            rec = {"kind": kind, "id": span_id, "parent": parent,
                   "t": t0 - self._t0, "dur": dur}
            rec.update(attrs)
            self._write(rec)

    def step_report(self, report) -> None:
        """Record a :class:`~.telemetry.StepReport` (or a plain dict)."""
        payload = report.to_json() if hasattr(report, "to_json") else report
        self.event("step_report", **payload)

    def metrics_snapshot(self, registry) -> None:
        """Record a registry snapshot (counters/gauges/timers/histograms)."""
        self.event("metrics", metrics=registry.snapshot())

    # -- lifecycle ---------------------------------------------------------

    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- readback ----------------------------------------------------------

    @staticmethod
    def read(path: str) -> List[Dict[str, Any]]:
        """All records in file order (children precede their parent span).

        A torn FINAL line — the one artifact a crash or a reader racing
        the writer can legitimately produce on an append-only file — is
        dropped silently; corruption anywhere else still raises."""
        with open(path) as f:
            lines = [ln.strip() for ln in f]
        while lines and not lines[-1]:
            lines.pop()
        out: List[Dict[str, Any]] = []
        for i, line in enumerate(lines):
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break
                raise
        return out


class NullEventLog:
    """Disabled sink: same surface as :class:`EventLog`, writes nothing."""

    path = None

    def event(self, kind: str, **attrs: Any) -> None:
        pass

    def span(self, kind: str, **attrs: Any):
        return contextlib.nullcontext(0)

    def step_report(self, report) -> None:
        pass

    def metrics_snapshot(self, registry) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullEventLog":
        return self

    def __exit__(self, *exc) -> None:
        pass


NULL_EVENT_LOG = NullEventLog()
