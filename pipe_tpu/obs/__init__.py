"""Observability: profiler scopes, bubble measurement, memory reporting."""

from .meters import (BubbleMeter, device_memory_report, measured_bubble_slope,
                     profile_trace, stage_busy_from_trace, stage_scope)

__all__ = ["BubbleMeter", "device_memory_report", "measured_bubble_slope",
           "profile_trace", "stage_busy_from_trace", "stage_scope"]
