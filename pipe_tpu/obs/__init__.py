"""Observability: metrics registry, structured events, profiler scopes,
bubble measurement, per-stage timeline attribution, memory reporting.

See ``docs/observability.md`` for the full subsystem tour.
"""

from .events import (EventLog, NULL_EVENT_LOG, NullEventLog, SPAN_KINDS)
from .fleet_obs import (FleetObserver, SloMonitor, SloTargets, TraceBuffer,
                        prometheus_text)
from .meters import (BubbleMeter, device_memory_report, measured_bubble_slope,
                     measured_bubble_two_point, profile_trace,
                     stage_busy_from_trace, stage_scope,
                     stage_timeline_from_trace)
from .telemetry import (Counter, EwmaTimer, Gauge, Histogram, MetricsRegistry,
                        StepReport, device_memory_peaks, get_registry,
                        null_registry, peak_flops_per_chip, set_registry,
                        train_flops_per_token)
from .tb_writer import ScalarWriter

__all__ = [
    "BubbleMeter", "device_memory_report", "measured_bubble_slope",
    "measured_bubble_two_point", "profile_trace", "stage_busy_from_trace",
    "stage_scope", "stage_timeline_from_trace",
    "EventLog", "NullEventLog", "NULL_EVENT_LOG", "SPAN_KINDS",
    "FleetObserver", "SloMonitor", "SloTargets", "TraceBuffer",
    "prometheus_text",
    "Counter", "EwmaTimer", "Gauge", "Histogram", "MetricsRegistry",
    "StepReport", "device_memory_peaks", "get_registry", "null_registry",
    "peak_flops_per_chip", "set_registry", "train_flops_per_token",
    "ScalarWriter",
]
