"""ctypes binding for the native corpus processor (csrc/pipetpu_io.cpp).

The performance path for host-side input processing: one C++ pass builds the
token-id stream and first-appearance vocabulary (the reference stack's data
loading likewise bottoms out in torchtext's native kernels). The library is
compiled on first use with g++ and cached next to the source; everything
falls back to the pure-Python pipeline (``data.lm_text``) when a toolchain
is unavailable, with identical token-for-token semantics (asserted by
``tests/test_native_io.py``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["native_available", "NativeCorpus", "process_corpus",
           "prefetch_available", "BatchPrefetcher"]

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SRC = os.path.join(_CSRC, "pipetpu_io.cpp")
_LIB = os.path.join(_CSRC, "libpipetpu_io.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build_lib(src: str, lib: str, *extra_flags: str) -> Optional[str]:
    """Compile a shared library if missing or stale; None on failure."""
    try:
        if (not os.path.exists(lib)
                or os.path.getmtime(lib) < os.path.getmtime(src)):
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                 *extra_flags, src, "-o", lib],
                check=True, capture_output=True, timeout=120)
        return lib
    except (OSError, subprocess.SubprocessError):
        return None


def _build() -> Optional[str]:
    return _build_lib(_SRC, _LIB)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _build()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        lib.ptio_from_bytes.restype = ctypes.c_void_p
        lib.ptio_from_bytes.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.ptio_from_file.restype = ctypes.c_void_p
        lib.ptio_from_file.argtypes = [ctypes.c_char_p]
        lib.ptio_num_tokens.restype = ctypes.c_int64
        lib.ptio_num_tokens.argtypes = [ctypes.c_void_p]
        lib.ptio_vocab_size.restype = ctypes.c_int32
        lib.ptio_vocab_size.argtypes = [ctypes.c_void_p]
        lib.ptio_copy_ids.restype = None
        lib.ptio_copy_ids.argtypes = [ctypes.c_void_p, ctypes.POINTER(
            ctypes.c_int32)]
        lib.ptio_token.restype = ctypes.c_char_p
        lib.ptio_token.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ptio_lookup.restype = ctypes.c_int32
        lib.ptio_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ptio_free.restype = None
        lib.ptio_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class NativeCorpus:
    """Token ids + vocabulary built by the C++ pass."""

    def __init__(self, handle: int, lib: ctypes.CDLL):
        self._h = handle
        self._lib = lib

    @classmethod
    def from_file(cls, path: str) -> "NativeCorpus":
        lib = _load()
        if lib is None:
            raise RuntimeError("native corpus library unavailable")
        h = lib.ptio_from_file(path.encode())
        if not h:
            raise FileNotFoundError(
                f"{path}: unreadable, non-seekable, or out of memory")
        return cls(h, lib)

    @classmethod
    def from_text(cls, text: str) -> "NativeCorpus":
        lib = _load()
        if lib is None:
            raise RuntimeError("native corpus library unavailable")
        data = text.encode()
        h = lib.ptio_from_bytes(data, len(data))
        if not h:
            raise MemoryError("native corpus build failed")
        return cls(h, lib)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.ptio_free(self._h)
            self._h = None

    @property
    def num_tokens(self) -> int:
        return int(self._lib.ptio_num_tokens(self._h))

    @property
    def vocab_size(self) -> int:
        return int(self._lib.ptio_vocab_size(self._h))

    def ids(self) -> np.ndarray:
        out = np.empty(self.num_tokens, np.int32)
        self._lib.ptio_copy_ids(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out

    def token(self, idx: int) -> str:
        raw = self._lib.ptio_token(self._h, idx)
        if raw is None:
            raise IndexError(idx)
        return raw.decode()

    def lookup(self, token: str) -> int:
        return int(self._lib.ptio_lookup(self._h, token.encode()))

    def vocab_list(self) -> List[str]:
        return [self.token(i) for i in range(self.vocab_size)]


def process_corpus(path: Optional[str] = None, text: Optional[str] = None
                   ) -> Tuple[np.ndarray, List[str]]:
    """(ids, vocab) via the native pass, falling back to pure Python.

    The native pass is used only for ASCII corpora — its lowercase and
    whitespace handling are byte-wise, while the Python tokenizer is
    Unicode-aware, so routing non-ASCII text natively would change ids.
    """
    if (path is None) == (text is None):
        raise ValueError("pass exactly one of path or text")
    if text is None:
        with open(path, encoding="utf-8") as f:
            text_content = f.read()
    else:
        text_content = text
    if native_available() and text_content.isascii():
        c = (NativeCorpus.from_file(path) if path is not None
             else NativeCorpus.from_text(text))
        return c.ids(), c.vocab_list()
    from . import lm_text
    lines = text_content.splitlines()
    vocab = lm_text.Vocab(map(lm_text.basic_english_tokenize, lines))
    return lm_text.data_process(lines, vocab), \
        [vocab.lookup_token(i) for i in range(len(vocab))]


# --- native batch prefetcher (csrc/pipetpu_prefetch.cpp) ---

_PF_SRC = os.path.join(_CSRC, "pipetpu_prefetch.cpp")
_PF_LIB = os.path.join(_CSRC, "libpipetpu_prefetch.so")

_pf_lib: Optional[ctypes.CDLL] = None
_pf_build_failed = False


def _load_prefetch() -> Optional[ctypes.CDLL]:
    global _pf_lib, _pf_build_failed
    with _lock:
        if _pf_lib is not None or _pf_build_failed:
            return _pf_lib
        path = _build_lib(_PF_SRC, _PF_LIB, "-pthread")
        if path is None:
            _pf_build_failed = True
            return None
        lib = ctypes.CDLL(path)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.ptpf_create.restype = ctypes.c_void_p
        lib.ptpf_create.argtypes = [i32p, ctypes.c_int64, ctypes.c_int64,
                                    ctypes.c_int64, ctypes.c_int64,
                                    i32p, i32p]
        lib.ptpf_num_batches.restype = ctypes.c_int64
        lib.ptpf_num_batches.argtypes = [ctypes.c_void_p]
        lib.ptpf_next.restype = ctypes.c_int64
        lib.ptpf_next.argtypes = [ctypes.c_void_p]
        lib.ptpf_release.restype = None
        lib.ptpf_release.argtypes = [ctypes.c_void_p]
        lib.ptpf_free.restype = None
        lib.ptpf_free.argtypes = [ctypes.c_void_p]
        _pf_lib = lib
        return _pf_lib


def prefetch_available() -> bool:
    return _load_prefetch() is not None


class BatchPrefetcher:
    """Iterator over (data, target) LM batches assembled by a C++ thread.

    Matches the trainer's ``get_batch`` walk exactly (``lm_text.get_batch``
    slice + transpose per full batch; tail batches are never yielded — the
    trainer breaks on them anyway), but the assembly runs on a producer
    thread writing into a ``depth``-slot ring of pre-allocated buffers, so
    batch prep overlaps device compute.

    Double-buffer contract: the arrays yielded for batch ``b`` are views
    into ring slot ``b % depth`` and are valid ONLY until the next
    ``__next__`` call — advancing the iterator releases the previous slot
    back to the producer, which may immediately start overwriting it.
    Callers that keep references across iterations must ``.copy()``
    (``Trainer._batches`` does).
    """

    def __init__(self, source: np.ndarray, bptt: int, depth: int = 2):
        lib = _load_prefetch()
        if lib is None:
            raise RuntimeError("native prefetch library unavailable")
        if source.ndim != 2:
            raise ValueError(f"source must be [nbatch, bsz], got "
                             f"{source.shape}")
        if bptt <= 0 or depth <= 0:
            raise ValueError("bptt and depth must be positive")
        self._lib = lib
        # keep the producer's input alive and contiguous for its lifetime
        self._source = np.ascontiguousarray(source, dtype=np.int32)
        nrows, bsz = self._source.shape
        self._data = np.empty((depth, bsz, bptt), np.int32)
        self._target = np.empty((depth, bsz, bptt), np.int32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        self._h = lib.ptpf_create(
            self._source.ctypes.data_as(i32p), nrows, bsz, bptt, depth,
            self._data.ctypes.data_as(i32p),
            self._target.ctypes.data_as(i32p))
        if not self._h:
            raise MemoryError("native prefetcher creation failed")
        self._outstanding = False
        self.num_batches = int(lib.ptpf_num_batches(self._h))

    def __iter__(self):
        return self

    def __next__(self):
        if self._h is None:
            raise StopIteration
        if self._outstanding:
            self._lib.ptpf_release(self._h)
            self._outstanding = False
        slot = int(self._lib.ptpf_next(self._h))
        if slot < 0:
            self.close()
            raise StopIteration
        self._outstanding = True
        return self._data[slot], self._target[slot]

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ptpf_free(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        self.close()
