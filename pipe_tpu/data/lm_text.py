"""Language-model text pipeline: tokenize → vocab → batchify → bptt batches.

Capability parity with the reference driver's data path (``main.py:76-113``),
which uses torchtext's WikiText-2 loader, ``basic_english`` tokenizer, and
``build_vocab_from_iterator``. torchtext is not available (and this machine
has no network), so this module reimplements the same semantics:

* :func:`basic_english_tokenize` — lowercase + punctuation isolation +
  whitespace split (the ``basic_english`` normalization contract);
* :class:`Vocab` — insertion-ordered by first appearance with ``<unk>``
  default index (``main.py:78-79``);
* :func:`data_process` — tokenize each line, drop empties, concatenate ids
  (``main.py:81-83``);
* :func:`batchify` — trim to a multiple of ``bsz`` and reshape to
  ``[nbatch, bsz]`` (``main.py:92-99``);
* :func:`get_batch` — ``(data[bsz, seq], flat targets)`` batch-first for the
  pipeline (``main.py:108-113``).

Corpus source: a text file if given, else :func:`synthetic_corpus` — a
deterministic Zipf-ish token stream so training and benchmarks run
hermetically (WikiText-2 itself cannot be fetched in this environment).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

__all__ = [
    "basic_english_tokenize",
    "Vocab",
    "data_process",
    "batchify",
    "get_batch",
    "num_batches",
    "synthetic_corpus",
    "load_corpus",
]

_PUNCT = re.compile(r"([.,!?()\'])")
_DROP = re.compile(r"[\"\;\:]")
_WS = re.compile(r"\s+")


def basic_english_tokenize(line: str) -> List[str]:
    """Lowercase, isolate punctuation, split on whitespace."""
    line = line.lower()
    line = _DROP.sub(" ", line)
    line = _PUNCT.sub(r" \1 ", line)
    return _WS.sub(" ", line).strip().split(" ") if line.strip() else []


class Vocab:
    """Token → id map with an ``<unk>`` default (``main.py:78-79``)."""

    UNK = "<unk>"

    def __init__(self, tokens_iter: Iterable[List[str]],
                 specials: Tuple[str, ...] = (UNK,),
                 min_freq: int = 1):
        freqs: Dict[str, int] = {}
        order: List[str] = []
        for toks in tokens_iter:
            for t in toks:
                if t not in freqs:
                    order.append(t)
                freqs[t] = freqs.get(t, 0) + 1
        self._itos: List[str] = list(specials)
        for t in order:
            if freqs[t] >= min_freq and t not in self._itos[:len(specials)]:
                self._itos.append(t)
        self._stoi = {t: i for i, t in enumerate(self._itos)}
        self._default = self._stoi[self.UNK]

    def __len__(self) -> int:
        return len(self._itos)

    def __getitem__(self, token: str) -> int:
        return self._stoi.get(token, self._default)

    def __call__(self, tokens: List[str]) -> List[int]:
        return [self[t] for t in tokens]

    def lookup_token(self, idx: int) -> str:
        return self._itos[idx]


def data_process(lines: Iterable[str], vocab: Vocab) -> np.ndarray:
    """Tokenize lines, drop empty ones, concatenate ids (``main.py:81-83``)."""
    chunks = []
    for line in lines:
        ids = vocab(basic_english_tokenize(line))
        if ids:
            chunks.append(np.asarray(ids, np.int32))
    if not chunks:
        return np.zeros((0,), np.int32)
    return np.concatenate(chunks)


def batchify(data: np.ndarray, bsz: int) -> np.ndarray:
    """Trim to a multiple of ``bsz``; reshape to ``[nbatch, bsz]``.

    Matches ``main.py:92-99``: the stream is cut into ``bsz`` contiguous
    lanes; row ``i`` holds the ``i``-th timestep of every lane.
    """
    nbatch = data.shape[0] // bsz
    data = data[:nbatch * bsz]
    return data.reshape(bsz, nbatch).T.copy()


def get_batch(source: np.ndarray, i: int, bptt: int
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Batch-first ``[bsz, seq]`` inputs and ``[bsz, seq]`` next-token targets.

    ``main.py:108-113`` returns ``data.t()`` (batch-first for Pipe) and a
    flat target vector; targets here stay ``[bsz, seq]`` because the loss is
    computed in-pipeline per micro-batch (``models.transformer_lm
    .loss_post_fn``) — flatten to match the reference exactly.
    """
    seq_len = min(bptt, source.shape[0] - 1 - i)
    data = source[i:i + seq_len].T
    target = source[i + 1:i + 1 + seq_len].T
    return np.ascontiguousarray(data), np.ascontiguousarray(target)


def num_batches(source: np.ndarray, bptt: int) -> int:
    return max(0, (source.shape[0] - 1) // bptt)


def synthetic_corpus(n_tokens: int = 200_000, vocab_size: int = 1000,
                     seed: int = 0) -> List[str]:
    """Deterministic Zipf-distributed pseudo-text, as lines of words.

    Stands in for WikiText-2 when no corpus file is available (no network in
    this environment); same downstream pipeline, hermetic and reproducible.
    """
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, vocab_size + 1)
    probs = 1.0 / ranks
    probs /= probs.sum()
    words = [f"w{i:04d}" for i in range(vocab_size)]
    ids = rng.choice(vocab_size, size=n_tokens, p=probs)
    lines = []
    pos = 0
    while pos < n_tokens:
        ln = int(rng.integers(8, 25))
        lines.append(" ".join(words[i] for i in ids[pos:pos + ln]))
        pos += ln
    return lines


def load_corpus(path: Optional[str] = None,
                splits: Tuple[float, float, float] = (0.8, 0.1, 0.1),
                **synth_kwargs):
    """(train_lines, val_lines, test_lines) from a file or the synthetic corpus."""
    if path is not None:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
    else:
        lines = synthetic_corpus(**synth_kwargs)
    n = len(lines)
    a = int(n * splits[0])
    b = a + int(n * splits[1])
    return lines[:a], lines[a:b], lines[b:]
