"""Input pipelines (tutorial-parity LM text processing)."""

from . import lm_text
from .lm_text import (Vocab, basic_english_tokenize, batchify, data_process,
                      get_batch, load_corpus, num_batches, synthetic_corpus)

__all__ = [
    "lm_text", "Vocab", "basic_english_tokenize", "batchify", "data_process",
    "get_batch", "load_corpus", "num_batches", "synthetic_corpus",
]
