"""Model-zoo driver: train any BASELINE config family end-to-end.

``python -m pipe_tpu.apps.zoo gpt2|bert|vit [options]`` builds the family's
pipelined factorization, picks an executor by ``--schedule``, and runs a
short synthetic-data training loop — the zoo analogue of the tutorial
driver (``python main.py <mode>``, reference ``main.py:164-169``), with the
BASELINE.json compositions as defaults:

* ``gpt2``: causal LM (config #3; pair with ``--schedule 1f1b``);
* ``bert``: MLM pretraining with 80/10/10 masking (config #4; pair with
  ``--schedule interleaved-1f1b``);
* ``vit``: image classification (config #5).

``--tiny`` (with ``--cpu N``) keeps it CI-sized; full-size configs are the
real 124M/340M/304M models.
"""

from __future__ import annotations

import argparse
import time


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("family", choices=["gpt2", "bert", "vit"])
    p.add_argument("--checkpoint", default="except_last",
                   choices=["never", "except_last", "always"])
    p.add_argument("--schedule", default="1f1b",
                   choices=["gpipe", "1f1b", "zb-h1", "interleaved-1f1b"])
    p.add_argument("--stages", type=int, default=2)
    p.add_argument("--chunks", type=int, default=4)
    p.add_argument("--interleave", type=int, default=2,
                   help="virtual stages per device (interleaved-1f1b)")
    p.add_argument("--steps", type=int, default=8,
                   help="training steps (>= 1)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--cpu", type=int, default=0,
                   help="force N virtual CPU devices (testing without TPU)")
    return p


def main(argv=None) -> int:
    parser = build_argparser()
    args = parser.parse_args(argv)
    if args.steps < 1:
        parser.error("--steps must be >= 1")
    if args.stages < 1 or args.interleave < 1:
        parser.error("--stages and --interleave must be >= 1")
    if args.cpu:
        from pipe_tpu.utils.platform import force_cpu_platform
        force_cpu_platform(args.cpu)

    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.core.schedule import InterleavedOneFOneBSchedule
    from pipe_tpu.models import (BertConfig, GPT2Config, PipelinedBERT,
                                 PipelinedGPT2, PipelinedViT, ViTConfig,
                                 mask_tokens)
    from pipe_tpu.parallel.interleaved import stack_interleaved_params
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params
    from pipe_tpu.utils.rng import make_key

    v = args.interleave if args.schedule == "interleaved-1f1b" else 1
    n_virtual = args.stages * v

    cfg_cls = {"gpt2": GPT2Config, "bert": BertConfig,
               "vit": ViTConfig}[args.family]
    cfg = cfg_cls()
    if args.tiny:
        cfg = cfg.tiny()
    # the model must factor into the virtual stage count
    if cfg.n_layers % n_virtual:
        adjusted = max(1, cfg.n_layers // n_virtual) * n_virtual
        print(f"note: n_layers {cfg.n_layers} -> {adjusted} to factor into "
              f"{n_virtual} virtual stages")
        cfg = dataclasses.replace(cfg, n_layers=adjusted)
    model_cls = {"gpt2": PipelinedGPT2, "bert": PipelinedBERT,
                 "vit": PipelinedViT}[args.family]
    model = model_cls(cfg, n_virtual)
    sp, prep, postp = model.init(make_key(0))
    stacked = (stack_interleaved_params(sp, args.stages) if v > 1
               else stack_stage_params(sp))

    mesh = make_mesh(args.stages, 1, devices=jax.devices()[:args.stages])

    def batch_for(step: int):
        key = make_key(1000 + step)
        if args.family == "vit":
            images = jax.random.normal(
                key, (args.batch, cfg.image_size, cfg.image_size,
                      cfg.channels))
            labels = jax.random.randint(jax.random.fold_in(key, 1),
                                        (args.batch,), 0, cfg.n_classes)
            return {"images": images, "labels": labels}
        tokens = jax.random.randint(key, (args.batch, cfg.seq_len),
                                    2, cfg.vocab, jnp.int32)
        if args.family == "bert":
            masked, weights = mask_tokens(jax.random.fold_in(key, 1),
                                          tokens, cfg)
            return {"tokens": masked, "targets": tokens,
                    "mlm_weights": weights}
        return {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}

    tx = optax.adam(args.lr)
    params = (stacked, prep, postp)
    opt_state = tx.init(params)

    if args.schedule == "gpipe":
        pipe = SpmdPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                            post_fn=model.loss_post_fn, post_with_batch=True,
                            checkpoint=args.checkpoint)

        @jax.jit
        def step_fn(params, opt_state, x, w, key):
            def loss_fn(p):
                rows = pipe(p[0], p[1], p[2], x, key=key, train=True)
                return jnp.sum(rows * w) / jnp.sum(w)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
    else:
        if args.schedule == "interleaved-1f1b" and v == 1:
            print("note: --interleave 1 makes interleaved-1f1b the plain "
                  "1f1b schedule")
        sched_obj = (InterleavedOneFOneBSchedule(interleave=v)
                     if v > 1 else args.schedule)
        sched = ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                                  post_fn=model.loss_post_fn,
                                  checkpoint=args.checkpoint,
                                  schedule=sched_obj)

        @jax.jit
        def step_fn(params, opt_state, x, w, key):
            loss, grads = sched.loss_and_grad(params[0], params[1],
                                              params[2], x, w, key=key)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

    print(f"{args.family}: {model.num_params(params):,} params, "
          f"{n_virtual} virtual stages on {args.stages} devices, "
          f"schedule={args.schedule}, checkpoint={args.checkpoint}")
    t_start = t0 = time.perf_counter()
    for b in range(args.steps):
        stacked_x, n_rows = mb.stack_scatter(batch_for(b), args.chunks)
        # zero-weight the rows stack_scatter padded (VERDICT r1 #7)
        w = mb.valid_row_mask(stacked_x, n_rows)
        params, opt_state, loss = step_fn(params, opt_state, stacked_x, w,
                                          make_key(b))
        l = float(loss)
        if b == 0:
            t0 = time.perf_counter()  # timing from step 2 (skip compile)
        print(f"| step {b + 1}/{args.steps} | loss {l:.4f}")
    if args.steps > 1:
        ms = (time.perf_counter() - t0) / (args.steps - 1) * 1000
    else:
        ms = (time.perf_counter() - t_start) * 1000  # compile-inclusive
    print(f"final loss {l:.4f} ({ms:.1f} ms/step)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
