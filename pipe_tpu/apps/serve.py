"""Serving driver: the continuous-batching engine under a live workload.

Runs :class:`~pipe_tpu.serve.ServeEngine` over either slot backend —
``--stages 1`` (default) is the single-device backend with ``--slots``
decode slots; ``--stages N`` keeps the weights stage-sharded and serves
through the pipeline ring (slots == ring groups, kept full across
admissions). Workload: ``--prompts-file`` (comma-separated token-id
prompts, one per line, all arriving at once) or a synthetic seeded
Poisson stream (``--requests``/``--rate``). Per-request results stream
to stdout as JSON lines the moment each request retires; the final line
is a summary with the engine's ``serve.*`` metrics (admitted/retired/
rejected counters, TTFT percentiles, queue-depth/occupancy gauges).
``--events`` additionally writes the request-span EventLog
(docs/observability.md).

``--replicas N`` (with ``--stages 1``) serves through the fleet
instead: N replicas behind one front queue with health-gated failover;
the summary gains per-replica lines and a fleet rollup, and SIGTERM
drains the whole fleet. The fleet observability plane
(docs/observability.md, "Fleet observability") rides along:
``--metrics-port`` serves the merged fleet registry as Prometheus text
(``/metrics``; plus ``/slo`` and ``/fleet`` JSON — what
``tools/fleet_top.py`` polls), ``--slo-*`` declare targets scored into
a machine-readable ``summary["slo"]`` verdict, and ``--trace-out``
writes the stitched per-request trace timelines (parent + shipped
child events) as JSONL. ``--fleet`` picks the replica transport:

* ``inproc`` (default) — engine replicas in this process, ticked
  serially by the router (the PR 7 behavior, byte-for-byte);
* ``thread`` — same engines, each under its own tick thread
  (``Router(async_tick=True)``): a slow replica no longer stalls its
  siblings' decode loops;
* ``proc`` — each replica a real OS process
  (:class:`~pipe_tpu.fleet.ProcessReplicaTransport`) with its own
  engine/jit cache/KV pool behind a length-prefixed socket protocol;
  needs ``--family lm`` without ``--resume``/``--spec-tokens`` (the
  child rebuilds the model from the spec + seed).

Usage:
    python -m pipe_tpu.apps.serve [--resume DIR] [--requests N --rate R]
        [--prompts-file F] [--slots S] [--stages N] [--replicas N]
        [--fleet inproc|thread|proc] [--journal DIR]
        [--eos ID] [--queue-capacity C] [--policy fifo|priority]
        [--timeout-s T] [--decode-chunk K] [--events F.jsonl] [--tiny]
        [--metrics-port P] [--trace-out F.jsonl]
        [--slo-ttft-p50 S] [--slo-ttft-p99 S] [--slo-e2e-p99 S]
        [--slo-goodput-min F] [--slo-deadline-miss-max F]
        [--slo-shed-max F]
        [--resident auto|on|off] [--resident-chunks R] [--spec-tokens K]
        [--draft ngram|truncated|tree] [--draft-stages N]
        [--spec-branches B] [--spec-adaptive]
        [--cpu N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .generate import DriverError, load_params


def _start_metrics_server(port, registry_fn, slo, observer):
    """Daemon-thread HTTP server on 127.0.0.1 exposing the fleet
    observability plane: ``/metrics`` renders ``registry_fn()`` as
    Prometheus text, ``/slo`` the verdict JSON, ``/fleet`` the
    per-replica JSON view (``tools/fleet_top.py`` polls these).
    Returns the server (``.server_address[1]`` is the bound port)."""
    import http.server
    import threading

    from ..obs.fleet_obs import prometheus_text

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0].rstrip("/") or "/metrics"
            try:
                if path == "/metrics":
                    body = prometheus_text(registry_fn()).encode()
                    ctype = "text/plain; version=0.0.4"
                elif path == "/slo":
                    body = json.dumps(slo.verdict(registry_fn())).encode()
                    ctype = "application/json"
                elif path == "/fleet":
                    per = (observer.per_replica()
                           if observer is not None else {})
                    body = json.dumps(
                        {str(k): v for k, v in per.items()}).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
            except Exception as e:               # surface, don't crash
                self.send_error(500, str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):               # keep stdout JSON-clean
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="metrics-http").start()
    return srv


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--resume", default=None,
                   help="checkpoint dir (train/state.py layout); default: "
                        "fresh random init")
    p.add_argument("--prompts-file", default=None,
                   help="serve these prompts (comma-separated ids per "
                        "line) instead of a synthetic stream")
    p.add_argument("--requests", type=int, default=16,
                   help="synthetic stream: number of requests")
    p.add_argument("--rate", type=float, default=0.0,
                   help="synthetic stream: Poisson arrivals/s "
                        "(0 = all at once)")
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--eos", type=int, default=None)
    p.add_argument("--stages", type=int, default=1,
                   help=">1: serve through the pipeline ring")
    p.add_argument("--replicas", type=int, default=1,
                   help=">1: run N engine replicas behind the fleet "
                        "Router (health-gated failover; single-device "
                        "backend only)")
    p.add_argument("--fleet", choices=["inproc", "thread", "proc"],
                   default="inproc",
                   help="replica transport with --replicas > 1: same-"
                        "process serial ticks (inproc), same-process "
                        "with one tick thread per replica (thread), or "
                        "one OS process per replica (proc)")
    p.add_argument("--slots", type=int, default=4,
                   help="decode slots (single-device backend; the ring "
                        "always has one slot per stage)")
    p.add_argument("--queue-capacity", type=int, default=64)
    p.add_argument("--policy", choices=["fifo", "priority"],
                   default="fifo")
    p.add_argument("--timeout-s", type=float, default=None,
                   help="per-request deadline")
    p.add_argument("--decode-chunk", type=int, default=4,
                   help="decode steps per host tick (ring: ring "
                        "revolutions per tick)")
    p.add_argument("--resident", choices=["auto", "on", "off"],
                   default="auto",
                   help="fused steady-state device loop: run up to "
                        "--resident-chunks decode chunks per launch "
                        "with on-device done-masking and early exit "
                        "(auto: on for accelerators, off on cpu)")
    p.add_argument("--resident-chunks", type=int, default=8,
                   help="max decode chunks per resident launch (ring: "
                        "revolutions)")
    p.add_argument("--spec-tokens", type=int, default=None,
                   help="speculative decode: K-token draft/verify per "
                        "resident round (needs --resident on/auto-on; "
                        "works on both backends)")
    p.add_argument("--draft", choices=["ngram", "truncated", "tree"],
                   default="ngram",
                   help="draft source for --spec-tokens: prompt-history "
                        "n-gram lookup (free), truncated-pipeline "
                        "(first --draft-stages stages + tied embedding "
                        "head), or multi-branch tree (single-device "
                        "backend only)")
    p.add_argument("--draft-stages", type=int, default=1,
                   help="stage depth of the truncated/tree draft — a "
                        "STRICT prefix of the model (with --stages 1 "
                        "the model is partitioned into draft-stages+1 "
                        "logical stages to carve one)")
    p.add_argument("--spec-branches", type=int, default=None,
                   help="tree draft: parallel branches per round (>= 2)")
    p.add_argument("--spec-adaptive", action="store_true",
                   help="per-slot acceptance-EWMA adaptive K over a "
                        "pre-traced ladder (single-device backend only)")
    p.add_argument("--events", default=None,
                   help="write the request-span EventLog here (.jsonl)")
    p.add_argument("--journal", default=None,
                   help="directory for the durable request journal "
                        "(fsync'd lifecycle WAL; a crashed controller "
                        "restarts from it via FleetController."
                        "from_journal). --fleet proc only")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve the merged fleet registry on "
                        "127.0.0.1:<port>: /metrics (Prometheus text), "
                        "/slo (verdict JSON), /fleet (per-replica JSON "
                        "view). 0 picks an ephemeral port (printed to "
                        "stderr)")
    p.add_argument("--trace-out", default=None,
                   help="with --replicas > 1: write the stitched "
                        "per-request trace timelines here (.jsonl)")
    p.add_argument("--slo-ttft-p50", type=float, default=None,
                   help="SLO target: TTFT p50 seconds")
    p.add_argument("--slo-ttft-p99", type=float, default=None,
                   help="SLO target: TTFT p99 seconds")
    p.add_argument("--slo-e2e-p99", type=float, default=None,
                   help="SLO target: end-to-end latency p99 seconds")
    p.add_argument("--slo-goodput-min", type=float, default=None,
                   help="SLO target: minimum ok/delivered fraction")
    p.add_argument("--slo-deadline-miss-max", type=float, default=None,
                   help="SLO target: max timed_out/delivered fraction")
    p.add_argument("--slo-shed-max", type=float, default=None,
                   help="SLO target: max shed/delivered fraction")
    p.add_argument("--tick-budget-s", type=float, default=None,
                   help="watchdog: count ticks slower than this "
                        "(resilience.watchdog_slow_ticks)")
    p.add_argument("--shed-ewma", type=float, default=None,
                   help="watchdog: deadline-miss EWMA above which "
                        "lowest-priority queued requests are shed")
    p.add_argument("--kv", choices=["slab", "paged"], default="slab",
                   help="KV memory: per-slot monolithic slab, or the "
                        "paged block pool (shared-prefix reuse + "
                        "chunked prefill)")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="rows per KV block with --kv paged")
    p.add_argument("--kv-pool-blocks", type=int, default=None,
                   help="pool size in blocks with --kv paged "
                        "(default: the slab's row footprint)")
    p.add_argument("--kv-offload", action="store_true",
                   help="with --kv paged: spill cold refcount-0 blocks "
                        "to host RAM under pressure and restore on "
                        "re-reference instead of hard-evicting")
    p.add_argument("--kv-offload-blocks", type=int, default=None,
                   help="host store capacity in blocks for --kv-offload "
                        "(default: the device pool size)")
    p.add_argument("--placement",
                   choices=["least_loaded", "session", "prefix"],
                   default="least_loaded",
                   help="fleet placement: least_loaded, session "
                        "pinning, or prefix (score replicas by matched "
                        "prefix depth x occupancy headroom)")
    p.add_argument("--kv-hot-refs", type=int, default=None,
                   help="fleet: replicate prefixes shared by at least "
                        "N live slots to a sibling proactively "
                        "(requires --kv paged; >= 2)")
    p.add_argument("--roles", default=None,
                   help="disaggregated fleet: comma-separated per-"
                        "replica roles (prefill|decode|mixed), length "
                        "== --replicas, e.g. 'prefill,decode,decode'. "
                        "Requests then flow prefill -> KV handoff -> "
                        "decode; 'auto' sizes the split with "
                        "suggest_roles. Pair with --kv paged so decode "
                        "replicas resume from shipped blocks")
    p.add_argument("--int8", action="store_true",
                   help="int8 weight-only quantized block weights")
    p.add_argument("--family", choices=["lm", "gpt2"], default="lm")
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--cpu", type=int, default=0,
                   help="force N virtual CPU devices (testing without TPU)")
    p.add_argument("--seed", type=int, default=0)
    return p


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    if args.cpu:
        from ..utils.platform import force_cpu_platform
        force_cpu_platform(args.cpu)

    import numpy as np

    from ..inference import GenerationConfig

    if args.family == "gpt2":
        from ..models.gpt2 import GPT2Config as _Cfg
        from ..models.gpt2 import PipelinedGPT2 as _Model
    else:
        from ..models.transformer_lm import LMConfig as _Cfg
        from ..models.transformer_lm import PipelinedLM as _Model

    model_cfg = _Cfg()
    if args.tiny:
        model_cfg = model_cfg.tiny()
    n_stages = max(args.stages, 1)
    # Pipeline-prefix drafts run "the first stage(s)", so the model must
    # be partitioned with a strict prefix to carve. The ring already is;
    # --stages 1 serves an unpartitioned model, so split it into
    # draft-stages+1 logical stages (same weights, nested differently —
    # the single-device backend flattens the stage list anyway).
    n_model_stages = n_stages
    if args.draft != "ngram" and n_stages == 1:
        n_model_stages = max(args.draft_stages, 1) + 1
    if model_cfg.n_layers % n_model_stages:
        what = (f"--stages {n_stages}" if n_model_stages == n_stages
                else f"--draft {args.draft} with --stages 1 partitions "
                     f"the model into --draft-stages + 1 = "
                     f"{n_model_stages} logical stages, which")
        print(f"{what} must divide the model's "
              f"{model_cfg.n_layers} layers", file=sys.stderr)
        return 2
    replicas = max(args.replicas, 1)
    if replicas > 1 and n_stages > 1:
        print("--replicas > 1 requires --stages 1 (the fleet router "
              "shards single-device engines)", file=sys.stderr)
        return 2

    if args.prompts_file:
        if not os.path.isfile(args.prompts_file):
            print(f"--prompts-file {args.prompts_file}: no such file",
                  file=sys.stderr)
            return 2
        with open(args.prompts_file) as f:
            try:
                prompts = [[int(t) for t in ln.split(",") if t.strip()]
                           for ln in f if ln.strip()]
            except ValueError:
                print("prompts must be comma-separated integer token ids",
                      file=sys.stderr)
                return 2
        if not prompts or any(
                not p or any(i < 0 or i >= model_cfg.vocab for i in p)
                for p in prompts):
            print(f"prompt ids must be in [0, {model_cfg.vocab})",
                  file=sys.stderr)
            return 2
    else:
        rng = np.random.RandomState(args.seed)
        lens = rng.choice((8, 12, 16, 24, 32), size=args.requests)
        prompts = [rng.randint(1, model_cfg.vocab, size=int(n)).tolist()
                   for n in lens]

    model = _Model(model_cfg, n_model_stages)
    try:
        params = load_params(args.resume, model_cfg, _Model,
                             n_model_stages, args.seed)
    except DriverError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.int8:
        from ..inference.quant import quantize_params
        sp_q, pre_q, post_q = params
        params = (quantize_params(sp_q), pre_q, post_q)
    gen_cfg = GenerationConfig(max_new_tokens=args.max_new,
                               temperature=args.temperature,
                               top_k=args.top_k, eos_token_id=args.eos)

    from ..obs.events import EventLog, NULL_EVENT_LOG
    from ..obs.telemetry import get_registry
    from ..serve import BucketSpec, QueueFull, RequestQueue, ServeEngine
    buckets = BucketSpec.pow2(min_len=8,
                              max_len=max(len(p) for p in prompts))
    # spec lane: verify-write slack on top of the request cap — the
    # chunk writes branches x (K-1) rows past the accepted frontier
    # (tree chunks carry every branch; linear drafts have one)
    max_len = buckets.max_len + args.max_new + (
        (args.spec_branches or 1) * (args.spec_tokens - 1)
        if args.spec_tokens else 0)
    if (args.kv_offload or args.kv_hot_refs is not None
            or args.placement == "prefix") and args.kv != "paged":
        print("--kv-offload/--kv-hot-refs/--placement prefix need "
              "--kv paged (the slab has no blocks to spill, share, or "
              "advertise)", file=sys.stderr)
        return 2
    roles = None
    if args.roles:
        replicas_n = max(args.replicas, 1)
        if args.roles == "auto":
            from ..fleet import suggest_roles
            roles = suggest_roles(
                replicas_n,
                prompt_len=max(len(p) for p in prompts),
                max_new_tokens=args.max_new).roles
        else:
            roles = [r.strip() for r in args.roles.split(",")]
        bad = [r for r in roles if r not in ("prefill", "decode", "mixed")]
        if bad or len(roles) != replicas_n:
            print(f"--roles must name one of prefill|decode|mixed per "
                  f"replica ({replicas_n} expected, got {roles})",
                  file=sys.stderr)
            return 2
        if replicas_n < 2:
            print("--roles needs --replicas >= 2 (one replica cannot "
                  "be split by phase)", file=sys.stderr)
            return 2
    kv_kwargs = {} if args.kv == "slab" else {
        "kv_block_size": args.kv_block_size,
        "kv_pool_blocks": args.kv_pool_blocks,
        "kv_offload": args.kv_offload,
        "kv_offload_blocks": args.kv_offload_blocks}
    resident = {"auto": "auto", "on": True, "off": False}[args.resident]
    spec_kwargs = dict(spec_tokens=args.spec_tokens, draft=args.draft,
                       draft_stages=args.draft_stages,
                       spec_branches=args.spec_branches,
                       spec_adaptive=args.spec_adaptive)
    # invalid spec combos (tree on the ring, draft flags without
    # --spec-tokens, out-of-range draft depth, ...) are rejected by the
    # backend/drafter ctors — surface the message, don't trace back
    try:
        if n_stages > 1:
            from ..parallel.mesh import make_mesh
            from ..parallel.spmd import stack_stage_params
            from ..serve import RingSlotBackend
            sp, pre, post = params
            backend = RingSlotBackend(
                make_mesh(n_stages, 1), model, stack_stage_params(sp),
                pre, post, max_len=max_len, gen=gen_cfg, buckets=buckets,
                revolutions=args.decode_chunk, resident=resident,
                resident_revolutions=args.resident_chunks,
                **spec_kwargs, **kv_kwargs)
        else:
            from ..serve import SingleDeviceSlotBackend
            backend = SingleDeviceSlotBackend(
                model, params, num_slots=args.slots, max_len=max_len,
                gen=gen_cfg, buckets=buckets,
                decode_chunk=args.decode_chunk, resident=resident,
                resident_chunks=args.resident_chunks,
                **spec_kwargs, **kv_kwargs)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    trace_buf = None
    if args.events:
        events = EventLog(args.events)
    elif args.trace_out and replicas > 1:
        # --trace-out without --events: hold the parent-side request
        # skeleton (queued/placed/delivered) in memory, or the stitched
        # timelines would carry child-side stages only
        from ..obs.fleet_obs import TraceBuffer
        trace_buf = TraceBuffer(maxlen=200_000)
        events = trace_buf
    else:
        events = NULL_EVENT_LOG

    def _make_watchdog():
        if args.tick_budget_s is None and args.shed_ewma is None:
            return None
        from ..resilience import TickWatchdog
        return TickWatchdog(tick_budget_s=args.tick_budget_s,
                            shed_ewma_threshold=args.shed_ewma)

    journal = None
    if args.journal and not (replicas > 1 and args.fleet == "proc"):
        # the journal exists to recover a crashed fleet controller; the
        # in-process engines die WITH their controller, so journaling
        # them would promise a restart that cannot happen
        print("--journal requires --fleet proc with --replicas > 1 "
              "(only the process fleet survives its controller)",
              file=sys.stderr)
        return 2

    if replicas > 1 and args.fleet == "proc":
        # process fleet: each replica a fresh interpreter built from a
        # plain-data spec — only the deterministic-init lm family can be
        # reconstructed child-side.
        if args.family != "lm" or args.resume or args.spec_tokens:
            print("--fleet proc requires --family lm without --resume/"
                  "--spec-tokens (children rebuild the model from the "
                  "spec + seed)", file=sys.stderr)
            return 2
        import dataclasses as _dc

        from ..fleet import (DisaggController, FleetController,
                             ProcessReplicaTransport, ReplicaSpec,
                             RouterPolicy)
        spec = ReplicaSpec(
            lm_cfg={f: getattr(model_cfg, f)
                    for f in ("vocab", "d_model", "nhead", "d_ff",
                              "n_layers", "dropout", "seq_len")},
            n_stages=1, init_seed=args.seed, num_slots=args.slots,
            max_len=max_len, buckets=list(buckets.lengths),
            decode_chunk=args.decode_chunk,
            queue_capacity=args.queue_capacity,
            gen=dict(max_new_tokens=args.max_new,
                     temperature=args.temperature, top_k=args.top_k,
                     eos_token_id=args.eos),
            **({"kv_block_size": args.kv_block_size,
                "kv_pool_blocks": args.kv_pool_blocks,
                "kv_offload": args.kv_offload,
                "kv_offload_blocks": args.kv_offload_blocks,
                "kv_hot_refs": args.kv_hot_refs}
               if args.kv == "paged" else {}))
        if roles is not None:
            transports = [
                ProcessReplicaTransport(_dc.replace(spec, role=role))
                for role in roles]
        else:
            transports = [ProcessReplicaTransport(spec)
                          for _ in range(replicas)]
        queue = RequestQueue(capacity=args.queue_capacity,
                             policy=args.policy)
        if args.journal:
            from ..fleet import RequestJournal
            journal = RequestJournal(args.journal)
        ctl_cls = DisaggController if roles is not None else FleetController
        eng = ctl_cls(
            transports, queue,
            policy=RouterPolicy(placement=args.placement,
                                kv_hot_refs=args.kv_hot_refs),
            event_log=events, journal=journal)
        if journal is not None:
            # journal each child's wire coordinates (and refresh the
            # fleet.json snapshot) so a restarted controller can
            # re-dial the RUNNING children instead of spawning
            for i, tr in enumerate(transports):
                journal.record_replica(i, **tr.rejoin_info())
    elif replicas > 1:
        # in-process fleet: one front queue, N engines each with its own
        # queue/watchdog, the Router in between. The single-replica path
        # below stays byte-for-byte what it was — Router absent means
        # zero overhead. --fleet thread gives each replica its own tick
        # thread; placement/health/delivery stay on the caller's thread.
        from ..serve import Router, SingleDeviceSlotBackend
        backends = [backend] + [
            SingleDeviceSlotBackend(
                model, params, num_slots=args.slots, max_len=max_len,
                gen=gen_cfg, buckets=buckets,
                decode_chunk=args.decode_chunk, resident=resident,
                resident_chunks=args.resident_chunks,
                **spec_kwargs, **kv_kwargs)
            for _ in range(replicas - 1)]
        engines = [ServeEngine(b,
                               RequestQueue(capacity=args.queue_capacity),
                               event_log=events,
                               watchdog=_make_watchdog(),
                               phase=(roles[i] if roles is not None
                                      else "mixed"))
                   for i, b in enumerate(backends)]
        queue = RequestQueue(capacity=args.queue_capacity,
                             policy=args.policy)
        from ..serve import RouterPolicy
        if roles is not None:
            from ..fleet import DisaggController, InProcessTransport
            eng = DisaggController(
                [InProcessTransport(e,
                                    async_tick=(args.fleet == "thread"))
                 for e in engines],
                queue, event_log=events,
                policy=RouterPolicy(placement=args.placement,
                                    kv_hot_refs=args.kv_hot_refs))
        else:
            eng = Router(engines, queue, event_log=events,
                         policy=RouterPolicy(placement=args.placement,
                                             kv_hot_refs=args.kv_hot_refs),
                         async_tick=(args.fleet == "thread"))
    else:
        queue = RequestQueue(capacity=args.queue_capacity,
                             policy=args.policy)
        eng = ServeEngine(backend, queue, event_log=events,
                          watchdog=_make_watchdog())

    # Fleet observability plane: the observer merges shipped/shared
    # replica metrics into one rollup registry; the SLO monitor scores
    # it; --metrics-port exposes both live (what fleet_top polls).
    from ..obs.fleet_obs import FleetObserver, SloMonitor, SloTargets
    slo = SloMonitor(SloTargets(
        ttft_p50_s=args.slo_ttft_p50, ttft_p99_s=args.slo_ttft_p99,
        e2e_p99_s=args.slo_e2e_p99, goodput_min=args.slo_goodput_min,
        deadline_miss_max=args.slo_deadline_miss_max,
        shed_max=args.slo_shed_max))
    observer = FleetObserver(eng, parent_events=(args.events or trace_buf)) \
        if replicas > 1 else None

    def _fleet_registry():
        return observer.rollup() if observer is not None \
            else get_registry()

    metrics_server = None
    if args.metrics_port is not None:
        metrics_server = _start_metrics_server(
            args.metrics_port, _fleet_registry, slo, observer)
        print(f"metrics: http://127.0.0.1:"
              f"{metrics_server.server_address[1]}/metrics",
              file=sys.stderr, flush=True)

    # Graceful drain on SIGTERM/SIGINT: live slots finish, queued work is
    # shed back to callers, new admissions stop — then a clean summary.
    # With --replicas this drains the WHOLE fleet (every engine).
    import signal as _signal

    def _drain_handler(signum, frame):
        eng.drain()

    for _sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            _signal.signal(_sig, _drain_handler)
        except (ValueError, OSError):
            pass  # not the main thread (embedded use) — skip handlers

    if args.prompts_file or args.rate <= 0:
        arrivals = [0.0] * len(prompts)
    else:
        rng = np.random.RandomState(args.seed + 1)
        arrivals = np.cumsum(
            rng.exponential(1.0 / args.rate, size=len(prompts))).tolist()

    from ..serve import EngineDraining

    t0 = time.monotonic()
    i = rejected = done = 0
    while i < len(prompts) or not eng.idle:
        if eng.draining:
            i = len(prompts)      # stop submitting; finish what's live
        now = time.monotonic() - t0
        while i < len(prompts) and arrivals[i] <= now:
            try:
                eng.submit(prompts[i], seed=args.seed + i,
                           timeout_s=args.timeout_s)
            except QueueFull:
                rejected += 1
            except EngineDraining:
                i = len(prompts)
                break
            i += 1
        if eng.idle and i < len(prompts):
            time.sleep(min(arrivals[i] - now, 0.005))
            continue
        for r in eng.tick():
            done += 1
            print(json.dumps({
                "request": r.request_id, "status": r.status,
                "finish_reason": r.finish_reason,
                "prompt_len": r.prompt_len, "tokens": r.tokens,
                "ttft_s": (round(r.ttft, 4)
                           if r.ttft is not None else None),
                "latency_s": round(r.latency, 4)}), flush=True)
    elapsed = time.monotonic() - t0

    from ..obs.telemetry import host_overhead_per_token
    snap = {k: v for k, v in get_registry().scalars().items()
            if k.startswith(("serve.", "resilience."))}
    summary = {
        "backend": (f"Fleet[{args.fleet}]({type(backend).__name__} x "
                    f"{replicas})"
                    if replicas > 1 else type(backend).__name__),
        "finished": done, "rejected": rejected,
        "drained": eng.draining,
        "elapsed_s": round(elapsed, 3),
        "resident": bool(getattr(backend, "resident", False)),
        "host_overhead_per_token_us": round(
            1e6 * host_overhead_per_token(), 2),
        "buckets": list(buckets.lengths), "metrics": snap}
    summary["slo"] = slo.verdict(_fleet_registry())
    if replicas > 1:
        def _rep_line(rep):
            line = {"replica": rep.index, "state": rep.state}
            try:
                # transport surfaces work for in-process AND process
                # replicas (a retired process transport may be gone)
                line["queue_depth"] = rep.transport.queue_depth
                line["live_slots"] = rep.transport.live_slots
            except Exception:
                line["queue_depth"] = line["live_slots"] = None
            return line
        summary["fleet"] = {
            "transport": args.fleet,
            "rollup": eng.counts(),
            "per_replica": [_rep_line(rep) for rep in eng.replicas]}
        eng.close()   # stops tick threads / shuts replica processes down
        # after close: the proc children ship their FINAL obs deltas on
        # the shutdown RPC, and every obs_view/ledger read below is
        # parent-side state that survives the replicas
        if observer is not None:
            per = observer.per_replica()
            summary["fleet"]["staleness_s"] = {
                str(i): v["staleness_s"] for i, v in per.items()}
            summary["fleet"]["reconcile"] = observer.reconcile()
            summary["slo"] = slo.verdict(_fleet_registry())
            if args.trace_out:
                # flush parent events so stitch() reads a complete log
                events.flush()
                summary["fleet"]["trace_records"] = \
                    observer.write_stitched(args.trace_out)
    if journal is not None:
        # the loop above ran to quiescence (drain included): everything
        # submitted is terminal, so stamp clean_shutdown — a restart on
        # this journal skips reconciliation entirely
        journal.close(clean=True)
    print(json.dumps({"summary": summary}))
    events.close()
    if metrics_server is not None:
        metrics_server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
