"""Generation driver: sample from a (trained or fresh) pipelined LM.

The reference has no inference path at all (``main.py`` trains and
evaluates loss only); this driver completes the loop: restore a
``train/state.py`` checkpoint (or init fresh weights), then sample
continuations with the KV-cached generator — single-device, or
ring-pipelined over a stage mesh when ``--stages > 1`` (the weights stay
in their stage-sharded training layout).

``--prompts-file`` (one comma-separated prompt per line) routes the
whole set through the continuous-batching serve engine
(``pipe_tpu/serve``) instead of naive per-prompt regeneration: mixed
lengths share a few bucketed prefill programs and ONE decode step, and
each response is still bitwise what a per-prompt generator call would
produce (the serve parity pin, ``tests/test_serve.py``).

Usage:
    python -m pipe_tpu.apps.generate [--resume DIR] [--prompt "ids,..."]
        [--prompts-file F] [--max-new N] [--temperature T] [--top-k K]
        [--eos ID] [--stages N] [--tiny] [--cpu N]
"""

from __future__ import annotations

import argparse
import os
import sys


class DriverError(Exception):
    """User-input problem: print the message, exit rc=2."""


def load_params(resume, model_cfg, _Model, n_stages, seed):
    """Fresh init, or params-only restore from a Trainer checkpoint into
    the SERVING stage layout (train and serve partitions need not
    match). Shared by the generate and serve drivers."""
    import jax
    import numpy as np

    model = _Model(model_cfg, n_stages)
    if not resume:
        return model.init(jax.random.key(seed))

    from ..parallel.spmd import stack_stage_params, unstack_stage_params
    from ..train.state import (checkpoint_params_layout,
                               read_params_layout, restore_params)
    # Trainer checkpoints hold stage-STACKED params in the layout of
    # the TRAINING stage count. Read that layout from metadata, restore
    # only the params subtree (optimizer state is training-only) with
    # an abstract template (no throwaway init), then regroup the flat
    # block sequence into the SERVING stage count.
    n_saved, lps_saved = checkpoint_params_layout(resume)
    if n_saved * lps_saved != model_cfg.n_layers:
        raise DriverError(
            f"checkpoint holds {n_saved}x{lps_saved} blocks but the "
            f"model has {model_cfg.n_layers} layers")
    saved_model = _Model(model_cfg, n_saved)

    def template_fn(key):
        sp, pre, post = saved_model.init(key)
        return (stack_stage_params(sp), pre, post)

    template = jax.eval_shape(template_fn, jax.random.key(0))
    ssp, pre, post = restore_params(resume, template)
    # detach from the TRAINING mesh placement the checkpoint recorded —
    # the serving mesh may have a different device count
    ssp, pre, post = jax.tree_util.tree_map(np.asarray, (ssp, pre, post))
    # flat layer order. Interleaved-schedule training stacks virtual
    # stages device-major-permuted; the layout record written by
    # Trainer.save tells us to invert that (the permutation convention
    # lives with its owner: parallel/interleaved.py). Without a
    # record, plain stage-major stacking is assumed.
    layout = read_params_layout(resume) or {}
    if layout.get("stacking") == "interleaved":
        from ..parallel.interleaved import unstack_interleaved_params
        d = n_saved // int(layout["interleave"])
        per_stage = unstack_interleaved_params(ssp, d)
    else:
        per_stage = unstack_stage_params(ssp, n_saved)
    flat = [blk for stage in per_stage for blk in stage]
    lps = model_cfg.n_layers // n_stages
    return ([flat[s * lps:(s + 1) * lps] for s in range(n_stages)],
            pre, post)


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--resume", default=None,
                   help="checkpoint dir (train/state.py layout); default: "
                        "fresh random init")
    p.add_argument("--prompt", default="1,2,3,4",
                   help="comma-separated prompt token ids (one sequence; "
                        "repeated to fill the batch)")
    p.add_argument("--prompts-file", default=None,
                   help="file with one comma-separated prompt per line; "
                        "the whole set is served through the "
                        "continuous-batching engine (overrides --prompt)")
    p.add_argument("--slots", type=int, default=4,
                   help="--prompts-file: decode slots for the serve "
                        "engine (single-device path; the ring always "
                        "uses one slot per stage)")
    p.add_argument("--eos", type=int, default=None,
                   help="eos token id: finished sequences stop early "
                        "(emit pad in the fixed-shape one-shot path)")
    p.add_argument("--batch", type=int, default=None,
                   help="batch size (default: stages, the ring group count)")
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--beams", type=int, default=1,
                   help=">1: beam search (deterministic; single-device "
                        "generator only)")
    p.add_argument("--int8", action="store_true",
                   help="int8 weight-only quantized block weights "
                        "(inference/quant.py)")
    p.add_argument("--family", choices=["lm", "gpt2"], default="lm",
                   help="model family: the tutorial-parity LM (sinusoid "
                        "positions, post-LN) or GPT-2 (learned positions, "
                        "pre-LN)")
    p.add_argument("--stages", type=int, default=1,
                   help=">1: ring-pipelined decode over a stage mesh")
    p.add_argument("--context-shards", type=int, default=1,
                   help=">1: context-sharded decode — the prompt KV cache "
                        "shards over a context axis (LM family only; "
                        "prompt length must divide)")
    p.add_argument("--tiny", action="store_true")
    p.add_argument("--cpu", type=int, default=0,
                   help="force N virtual CPU devices (testing without TPU)")
    p.add_argument("--seed", type=int, default=0)
    return p


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    if args.cpu:
        from ..utils.platform import force_cpu_platform
        force_cpu_platform(args.cpu)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..inference import GenerationConfig, Generator

    if args.family == "gpt2":
        from ..models.gpt2 import GPT2Config as _Cfg
        from ..models.gpt2 import PipelinedGPT2 as _Model
    else:
        from ..models.transformer_lm import LMConfig as _Cfg
        from ..models.transformer_lm import PipelinedLM as _Model

    model_cfg = _Cfg()
    if args.tiny:
        model_cfg = model_cfg.tiny()
    n_stages = max(args.stages, 1)

    # validate cheap inputs before any model/parameter materialization —
    # every bad argument exits via the clean rc=2 stderr path, never a
    # raw constructor traceback
    if model_cfg.n_layers % n_stages:
        print(f"--stages {n_stages} must divide the model's "
              f"{model_cfg.n_layers} layers", file=sys.stderr)
        return 2
    if args.prompts_file:
        if not os.path.isfile(args.prompts_file):
            print(f"--prompts-file {args.prompts_file}: no such file",
                  file=sys.stderr)
            return 2
        with open(args.prompts_file) as f:
            lines = [ln for ln in f if ln.strip()]
        sources = lines or ["" ]
    else:
        sources = [args.prompt]
    many = []
    for ln in sources:
        try:
            ids = [int(t) for t in ln.split(",") if t.strip()]
        except ValueError:
            print("prompt must be comma-separated integer token ids",
                  file=sys.stderr)
            return 2
        if not ids or any(i < 0 or i >= model_cfg.vocab for i in ids):
            print(f"prompt ids must be in [0, {model_cfg.vocab})",
                  file=sys.stderr)
            return 2
        many.append(ids)
    ids = many[0]
    if args.eos is not None and (args.eos < 0 or args.eos >= model_cfg.vocab):
        print(f"--eos must be in [0, {model_cfg.vocab})", file=sys.stderr)
        return 2
    if args.eos is not None and args.beams > 1:
        print("--eos with beam search is not implemented", file=sys.stderr)
        return 2
    if args.prompts_file and (args.beams > 1 or args.context_shards > 1):
        print("--prompts-file serves through the slot engine: beams and "
              "context shards are single-shot-generator-only",
              file=sys.stderr)
        return 2
    batch = args.batch if args.batch is not None else n_stages
    if n_stages > 1 and batch % n_stages:
        print(f"--batch {batch} must divide into --stages {n_stages} "
              "ring groups", file=sys.stderr)
        return 2
    if args.resume and not os.path.isdir(args.resume):
        print(f"--resume {args.resume}: no such directory", file=sys.stderr)
        return 2
    if args.beams > 1 and n_stages > 1:
        print("--beams > 1 is single-device only (the ring decoder does "
              "not reorder beams)", file=sys.stderr)
        return 2
    n_ctx = max(args.context_shards, 1)
    if n_ctx > 1:
        if (n_stages > 1 or args.beams > 1 or args.int8
                or args.family != "lm"):
            print("--context-shards composes only with the plain LM "
                  "single-stage float path", file=sys.stderr)
            return 2
        if len(ids) % n_ctx:
            print(f"prompt length {len(ids)} must divide over "
                  f"{n_ctx} context shards", file=sys.stderr)
            return 2

    model = _Model(model_cfg, n_stages)

    try:
        params = load_params(args.resume, model_cfg, _Model, n_stages,
                             args.seed)
    except DriverError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.int8:
        from ..inference.quant import quantize_params
        sp_q, pre_q, post_q = params
        params = (quantize_params(sp_q), pre_q, post_q)
    gen_cfg = GenerationConfig(max_new_tokens=args.max_new,
                               temperature=args.temperature,
                               top_k=args.top_k, num_beams=args.beams,
                               eos_token_id=args.eos)
    key = jax.random.key(args.seed + 1)

    if args.prompts_file:
        # the serve engine: bucketed prefill + one shared decode step
        # for the whole set, responses bitwise equal to per-prompt
        # generator calls (tests/test_serve.py)
        from ..serve import BucketSpec, ServeEngine
        buckets = BucketSpec.pow2(min_len=8,
                                  max_len=max(len(p) for p in many))
        max_len = buckets.max_len + args.max_new
        if n_stages > 1:
            from ..parallel.mesh import make_mesh
            from ..parallel.spmd import stack_stage_params
            from ..serve import RingSlotBackend
            sp, pre, post = params
            backend = RingSlotBackend(
                make_mesh(n_stages, 1), model, stack_stage_params(sp),
                pre, post, max_len=max_len, gen=gen_cfg, buckets=buckets)
        else:
            from ..serve import SingleDeviceSlotBackend
            backend = SingleDeviceSlotBackend(
                model, params, num_slots=args.slots, max_len=max_len,
                gen=gen_cfg, buckets=buckets)
        eng = ServeEngine(backend)
        seeds = [args.seed + 1] * len(many)
        for resp in eng.serve(many, seeds=seeds):
            print(",".join(str(int(t)) for t in resp.tokens))
        return 0

    prompt = jnp.asarray([ids] * batch, jnp.int32)

    if n_ctx > 1:
        from ..inference.long_context import ContextShardedGenerator
        from ..models.long_context_lm import ContextParallelLM
        from ..parallel.mesh import make_mesh
        cp = ContextParallelLM(model_cfg, n_stages)
        out = ContextShardedGenerator(
            make_mesh(1, 1, n_context=n_ctx), cp, gen_cfg).generate(
            params, prompt, key=key)
    elif n_stages > 1:
        from ..inference.pipelined import PipelinedGenerator
        from ..parallel.mesh import make_mesh
        from ..parallel.spmd import stack_stage_params
        sp, pre, post = params
        mesh = make_mesh(n_stages, 1)
        out = PipelinedGenerator(mesh, model, gen_cfg).generate(
            stack_stage_params(sp), pre, post, prompt, key=key)
    else:
        out = Generator(model, gen_cfg).generate(params, prompt, key=key)

    for row in np.asarray(out):
        print(",".join(str(int(t)) for t in row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
