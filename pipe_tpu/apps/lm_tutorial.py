"""Tutorial driver: the reference ``main.py`` flow, TPU-native.

Parity walkthrough (reference ``main.py``):
  corpus → tokenizer → vocab → batchify (``main.py:76-105``) →
  Transformer LM (emsize 2048, nhid 2048, nlayers 16, nhead 32, dropout 0.2,
  ``main.py:115-120``) → pipeline over stages with chunks=4
  (``main.py:162-171``) → Adam + StepLR + clip, ~8·bptt tokens
  (``main.py:182-234``) → optional profiler trace (``main.py:196-204``).

Usage (mirrors ``python main.py <checkpoint-mode>``, ``main.py:164-169``):
    python -m pipe_tpu.apps.lm_tutorial <never|except_last|always>
        [--corpus FILE] [--steps N] [--stages N] [--tiny] [--profile DIR]
        [--save DIR] [--resume DIR] [--cpu N]
"""

from __future__ import annotations

import argparse
import sys


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("checkpoint", choices=["never", "except_last", "always"],
                   help="activation-checkpoint mode (main.py:164-169)")
    p.add_argument("--corpus", default=None,
                   help="text file; default: deterministic synthetic corpus")
    p.add_argument("--steps", type=int, default=8,
                   help="train steps (~8·bptt tokens like main.py:194)")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--stages", type=int, default=2)
    p.add_argument("--chunks", type=int, default=4)
    p.add_argument("--schedule",
                   choices=["gpipe", "1f1b", "zb-h1", "interleaved",
                            "interleaved-1f1b"],
                   default="gpipe")
    p.add_argument("--lr", type=float, default=None,
                   help="override the reference's Adam lr=5.0 (main.py:183), "
                        "which diverges at full scale; try 1e-4")
    p.add_argument("--interleave", type=int, default=2,
                   help="virtual stages per device (interleaved schedule)")
    p.add_argument("--plan", default=None,
                   help="auto-planner front door (docs/planning.md): "
                        "'auto' searches schedule x chunks x interleave "
                        "under the planner's cost model and overrides "
                        "--schedule/--chunks; a path loads a saved "
                        "PLAN json (tools/plan_bench.py)")
    p.add_argument("--tiny", action="store_true",
                   help="tiny model config (CI / CPU-sized)")
    p.add_argument("--profile", default=None,
                   help="jax.profiler trace dir (main.py:196-204 equivalent)")
    p.add_argument("--save", default=None, help="checkpoint dir to save into")
    p.add_argument("--resume", default=None, help="checkpoint dir to resume")
    p.add_argument("--autosave", default=None,
                   help="checkpoint dir for preemption-aware autosave "
                        "(SIGTERM finishes the step, saves, exits cleanly)")
    p.add_argument("--cpu", type=int, default=0,
                   help="force N virtual CPU devices (testing without TPU)")
    return p


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    if args.cpu:
        from pipe_tpu.utils.platform import force_cpu_platform
        force_cpu_platform(args.cpu)

    import dataclasses

    import jax

    from pipe_tpu.data import lm_text
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.train.loop import Trainer, TrainerConfig
    from pipe_tpu.train.state import restore_checkpoint

    train_lines, val_lines, _ = lm_text.load_corpus(args.corpus)
    vocab = lm_text.Vocab(map(lm_text.basic_english_tokenize, train_lines))
    train_ids = lm_text.data_process(train_lines, vocab)
    val_ids = lm_text.data_process(val_lines, vocab)

    model_cfg = LMConfig(vocab=max(len(vocab), 2))
    if args.tiny:
        model_cfg = dataclasses.replace(
            model_cfg.tiny(), vocab=max(len(vocab), 2),
            n_layers=2 * args.stages)
    cfg = TrainerConfig(chunks=args.chunks, checkpoint=args.checkpoint,
                        n_stages=args.stages, schedule=args.schedule,
                        interleave=args.interleave, plan=args.plan)
    if args.tiny:
        cfg = dataclasses.replace(cfg, batch_size=8, eval_batch_size=8,
                                  bptt=model_cfg.seq_len, lr=1e-3)
    if args.lr is not None:  # explicit --lr beats the tiny default
        cfg = dataclasses.replace(cfg, lr=args.lr)
    if args.schedule in ("interleaved", "interleaved-1f1b") and args.tiny:
        model_cfg = dataclasses.replace(
            model_cfg, n_layers=args.stages * args.interleave)

    train_data = lm_text.batchify(train_ids, cfg.batch_size)
    val_data = lm_text.batchify(val_ids, cfg.eval_batch_size)

    trainer = Trainer(model_cfg, cfg)
    if args.plan:
        rc = trainer.cfg
        line = (f"plan resolved: schedule={rc.schedule} chunks={rc.chunks} "
                f"interleave={rc.interleave} checkpoint={rc.checkpoint}")
        if rc.plan.profile_source != "uniform":
            # uniform (analytic) profiles rank in abstract units — only a
            # measured profile's prediction is honest wall time
            line += (f" (predicted {rc.plan.predicted_step_s * 1e3:.2f} "
                     f"ms/step, "
                     f"{rc.plan.predicted_peak_bytes / 1e6:.1f} MB/device)")
        print(line)
    if args.autosave:
        trainer.install_autosave(args.autosave)
    state = trainer.init_state()
    if args.resume:
        state = restore_checkpoint(args.resume, state)
        print(f"resumed from step {int(state.step)}")
    print(f"Total parameters in model: {trainer.num_params(state):,}")

    import contextlib

    from pipe_tpu.obs import profile_trace

    metrics = {"loss": float("nan"), "sec_per_step": float("nan")}
    with (profile_trace(args.profile) if args.profile
          else contextlib.nullcontext()):
        for epoch in range(args.epochs):
            state, metrics = trainer.train_epoch(
                train_data, epoch=epoch, state=state,
                max_steps=args.steps, log_every=max(args.steps // 4, 1))
            if trainer._autosave_pending():
                break  # preemption: checkpoint written, exit cleanly
    if args.profile:
        print(f"profiler trace written to {args.profile}")

    if val_data.shape[0] > cfg.bptt:
        val_loss = trainer.evaluate(val_data, state, max_steps=4)
        print(f"val loss {val_loss:.3f}")
    if args.save:
        trainer.save(args.save, state)  # records the stage-stack layout
        print(f"checkpoint saved to {args.save} @ step {int(state.step)}")
    print(f"final train loss {metrics['loss']:.3f} "
          f"({metrics['sec_per_step']*1000:.1f} ms/step)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
