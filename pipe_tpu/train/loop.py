"""Training loop for the pipelined Transformer LM (tutorial parity).

Reference driver semantics (``main.py:180-234,273``): Adam(lr=5.0) +
StepLR(step=1, gamma=0.95), grad-clip 0.5, CrossEntropy on the last stage,
~8·bptt tokens per "epoch", train per checkpoint mode. Re-idiomized: one
jitted train step (forward pipeline + in-pipeline loss + backward + clip +
Adam) over the SPMD executor, metrics to stdout — step loss, tokens/s,
and the analytic pipeline-bubble fraction (the BASELINE.md north-star).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
import warnings
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core import microbatch as mb
from ..core.schedule import bubble_fraction
from ..models.transformer_lm import LMConfig, PipelinedLM
from ..obs import events as ev
from ..obs.meters import profile_trace
from ..obs.telemetry import (StepReport, device_memory_peaks, get_registry,
                             peak_flops_per_chip)
from ..parallel.mesh import make_mesh
from ..parallel.spmd import SpmdPipeline, stack_stage_params
from ..data import lm_text
from ..utils.platform import sync_if_forced_cpu
from ..utils.rng import make_key
from .state import TrainState

__all__ = ["TrainerConfig", "Trainer"]


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """Driver hyperparameters (reference ``main.py:101-120,182-185``)."""

    batch_size: int = 32
    # Reference uses 10 (main.py:84); default 8 here so eval batches divide
    # into `chunks` micro-batches without zero-padding skewing the mean loss.
    eval_batch_size: int = 8
    bptt: int = 128
    chunks: int = 4
    checkpoint: str = "except_last"
    n_stages: int = 2
    n_data: int = 1
    lr: float = 5.0            # reference main.py:183 (Adam at lr=5.0, sic)
    lr_gamma: float = 0.95     # StepLR(1.0, gamma=0.95), main.py:185
    grad_clip: float = 0.5     # main.py:219
    seed: int = 1234
    schedule: str = "gpipe"    # gpipe | 1f1b | zb-h1 | interleaved
                               # | interleaved-1f1b
    # Adam first-moment storage dtype: 'bfloat16' halves the m-moment HBM
    # traffic — measured ~4% step-time win at the 520M bench scale
    # (MFU_SWEEP_r04.jsonl, docs/mfu_roofline.md); None keeps f32.
    mu_dtype: Optional[str] = None
    interleave: int = 2        # virtual stages per device (interleaved only)
    # Directory for TensorBoard scalar event files (SURVEY §5 "stdout +
    # TensorBoard scalars"); None disables. Scalars mirror the stdout log
    # lines (train/loss, train/ppl, train/tok_s, train/ms_batch, train/lr,
    # pipeline/bubble) plus per-epoch train/epoch_loss and eval/loss.
    tb_dir: Optional[str] = None
    # ZeRO-1: shard Adam's moments over the data axis (each data replica
    # owns 1/n_data of the optimizer state; the update runs sharded and the
    # refreshed params are all-gathered — see train/zero.py). Layout-only:
    # matches the replicated optimizer up to float reduction order.
    zero: bool = False
    # Ring depth for the native batch prefetcher (C++ producer thread
    # assembling batches off the hot loop, data/native.py BatchPrefetcher);
    # 0 = assemble inline with get_batch (identical batches either way —
    # asserted in tests/test_prefetch.py). Falls back to inline assembly
    # when no C++ toolchain is available.
    prefetch_depth: int = 0
    # Unified telemetry (docs/observability.md): directory receiving the
    # structured JSONL event log (`events.jsonl` — step spans + per-step
    # StepReport records) and periodic profiler traces. None disables —
    # the loop then talks to no-op sinks (no file writes, no clock reads).
    telemetry_dir: Optional[str] = None
    # With telemetry_dir set: capture a profiler trace of one step every N
    # steps into telemetry_dir/trace_step{N} (0 disables). Feed captures to
    # tools/timeline_report.py for per-stage busy/idle attribution.
    profile_every: int = 0
    # Anomaly detection + recovery (docs/resilience.md): a
    # resilience.ResilienceConfig arms the guarded train step (in-jit
    # finiteness/loss-spike check, where-select skip-step), the bounded
    # rewind controller and data-iterator retry. None — the default —
    # keeps the train step program byte-identical to the unguarded build
    # (pinned in tests/test_resilience.py).
    resilience: Optional[Any] = None
    # Elastic degraded-mode training (docs/resilience.md): a
    # resilience.ElasticConfig arms the elastic train step — the guarded
    # step plus a traced stage-kill channel and a per-stage gradient
    # heartbeat in the aux carry — and the buddy-replication controller
    # that snapshots every stage's shard to its ring neighbor. On a
    # persistently-silent stage the epoch raises
    # resilience.StageLost; resilience.replan_after_loss rebuilds the
    # run over the n-1 survivors (see tools/elastic_bench.py). Requires
    # ``resilience``; None — the default — adds nothing to the program
    # (pinned in tests/test_elastic.py).
    elastic: Optional[Any] = None
    # Auto-planner front door (core/planner.py, docs/planning.md): a
    # planner ``Plan``, a path to a saved PLAN json, or the string
    # "auto". Resolved in Trainer.__init__ BEFORE executor dispatch: the
    # plan's schedule / chunks (m) / interleave / checkpoint replace the
    # corresponding fields here. "auto" searches the Trainer-supported
    # schedule families over an analytic uniform profile — PipelinedLM's
    # stage bodies are homogeneous, so uniform relative costs are exact —
    # at this config's stage count, batch size and checkpoint mode.
    # None (default): the hand-picked fields below stand.
    plan: Optional[Any] = None
    # Per-device memory cap (bytes) handed to the planner's search when
    # plan="auto"; None = uncapped.
    plan_memory_cap: Optional[int] = None


def _resolve_plan_config(model_cfg: LMConfig,
                         cfg: TrainerConfig) -> TrainerConfig:
    """Fold a planner Plan into the TrainerConfig (cfg.plan is set).

    "auto" runs the search here — schedule family × m × interleave over
    an analytic uniform profile (homogeneous PipelinedLM stage bodies),
    serialized cost mode on CPU hosts, parallel on real accelerators —
    restricted to the families this Trainer can execute. A Plan object or
    saved-plan path is adopted as-is (its schedule must be one the
    Trainer dispatches on)."""
    from ..core.planner import Plan, search, uniform_profile

    plan = cfg.plan
    if isinstance(plan, str) and plan != "auto":
        plan = Plan.load(plan)
    if plan == "auto":
        mode = ("serialized"
                if jax.devices()[0].platform == "cpu" else "parallel")
        # Per-layer analytic sizes: one boundary activation row is
        # [bptt, d_model] f32; transformer-block params are the attention
        # (4 d^2) + FFN (2 d d_ff) matmuls.
        act = cfg.bptt * model_cfg.d_model * 4
        p_layer = (4 * model_cfg.d_model ** 2
                   + 2 * model_cfg.d_model * model_cfg.d_ff) * 4
        prof = uniform_profile(
            model_cfg.n_layers, rows=1, mode=mode,
            layer_act_bytes=act, layer_param_bytes=p_layer)
        m_cands = sorted({m for m in (2, 4, 8, 16, 32, cfg.chunks)
                          if m > 0 and cfg.batch_size % m == 0})
        plans = search(
            prof, n_devices=cfg.n_stages, m_candidates=m_cands,
            batch_rows=cfg.batch_size,
            schedules=("gpipe", "1f1b", "zb-h1", "interleaved-1f1b"),
            interleave_candidates=(cfg.interleave,),
            checkpoint=cfg.checkpoint,
            memory_cap_bytes=cfg.plan_memory_cap,
            uniform_only=True)
        if not plans:
            raise ValueError(
                "plan='auto' found no feasible plan: every candidate "
                "failed verification, phase compilation, or the "
                "plan_memory_cap — raise the cap or hand-pick a config")
        plan = plans[0]
    widths = set(plan.balance)
    if len(widths) > 1:
        warnings.warn(
            f"plan balance {list(plan.balance)} is non-uniform; the "
            f"Trainer's PipelinedLM factors layers uniformly over "
            f"virtual stages, so only the plan's stage COUNT is honored "
            f"here (drive Pipe(plan=...) for heterogeneous cuts)",
            stacklevel=3)
    kw: Dict[str, Any] = {"plan": plan, "schedule": plan.schedule,
                          "chunks": plan.m, "checkpoint": plan.checkpoint,
                          "n_stages": plan.n_devices}
    if plan.v > 1:
        kw["interleave"] = plan.v
    return dataclasses.replace(cfg, **kw)


class Trainer:
    """Builds the mesh, model, optimizer and the jitted step; runs epochs."""

    def __init__(self, model_cfg: LMConfig, cfg: TrainerConfig,
                 devices: Optional[List[jax.Device]] = None,
                 chaos=None):
        self.model_cfg = model_cfg
        if cfg.plan is not None:
            cfg = _resolve_plan_config(model_cfg, cfg)
        self.cfg = cfg
        # Fault injection (resilience.ChaosPlan): the activation hook
        # wraps the model's pre_fn ONLY when a plan is supplied, so the
        # default build traces the exact original functions.
        self.chaos = chaos

        def _mk_model(n_stages: int) -> PipelinedLM:
            m = PipelinedLM(model_cfg, n_stages)
            if chaos is not None:
                from ..resilience.chaos import wrap_pre_fn, wrap_stage_fn
                m.pre_fn = wrap_pre_fn(m.pre_fn)
                m.stage_fn = wrap_stage_fn(m.stage_fn)
            return m

        self.mesh = make_mesh(cfg.n_stages, cfg.n_data, devices=devices)
        if cfg.schedule == "interleaved":
            # n_stages devices, each hosting `interleave` virtual stages:
            # the model factors into n_stages*interleave stage bodies.
            from ..parallel.interleaved import InterleavedSpmdPipeline
            self.n_virtual = cfg.n_stages * cfg.interleave
            self.model = _mk_model(self.n_virtual)
            self.pipe = InterleavedSpmdPipeline(
                self.mesh, self.model.stage_fn, v=cfg.interleave,
                pre_fn=self.model.pre_fn, post_fn=self.model.loss_post_fn,
                post_with_batch=True, checkpoint=cfg.checkpoint)
        elif cfg.schedule in ("1f1b", "interleaved-1f1b", "zb-h1"):
            # True 1F1B: the manual fwd+bwd executor caps live activations at
            # min(chunks, n_stages) per stage and applies the exact
            # per-micro-batch checkpoint policy (parallel.scheduled).
            # interleaved-1f1b hosts `interleave` virtual stages per device
            # (both passes from one static table; see core.schedule).
            from ..core.schedule import InterleavedOneFOneBSchedule
            from ..parallel.scheduled import ScheduledPipeline
            split_kw = {}
            if cfg.schedule == "interleaved-1f1b":
                sched = InterleavedOneFOneBSchedule(
                    interleave=cfg.interleave)
                self.n_virtual = cfg.n_stages * cfg.interleave
            else:
                # "1f1b" or "zb-h1" (split-backward zero-bubble tables).
                # zb-h1's recommendation is GATED on the committed cost
                # model (docs/zb_crossover.md): it beats 1f1b on parallel
                # hardware only when the measured split overhead sigma is
                # below the config's breakeven sigma*. With the structural
                # B/W split (split_stage="auto", core/remat.py) the cpu8
                # recalibration measures sigma <= 1.41 — below every swept
                # breakeven (ZB_CROSSOVER_r05.json) — so the Trainer
                # engages the split whenever the checkpoint mode allows
                # it; recompute modes fall back to the fused backward at
                # B (W slots idle) and warn.
                if cfg.schedule == "zb-h1":
                    if cfg.checkpoint == "never":
                        split_kw["split_stage"] = "auto"
                    else:
                        from ..obs.zb_model import crossover
                        row = crossover(cfg.chunks, cfg.n_stages,
                                        sigma=1.0)
                        warnings.warn(
                            f"zb-h1 at (m={cfg.chunks}, "
                            f"n={cfg.n_stages}) with "
                            f"checkpoint={cfg.checkpoint!r}: the "
                            f"structural B/W split needs "
                            f"checkpoint='never', so the fused backward "
                            f"runs at B and the zero-bubble advantage "
                            f"(breakeven sigma* "
                            f"{row['breakeven_sigma']:.2f}, measured "
                            f"split sigma <= 1.41 — docs/zb_crossover.md) "
                            f"is forfeited.", stacklevel=2)
                sched = cfg.schedule
                self.n_virtual = cfg.n_stages
            self.model = _mk_model(self.n_virtual)
            self.pipe = ScheduledPipeline(
                self.mesh, self.model.stage_fn, pre_fn=self.model.pre_fn,
                post_fn=self.model.loss_post_fn, checkpoint=cfg.checkpoint,
                schedule=sched, **split_kw)
        elif cfg.schedule == "gpipe":
            self.n_virtual = cfg.n_stages
            self.model = _mk_model(cfg.n_stages)
            self.pipe = SpmdPipeline(
                self.mesh, self.model.stage_fn, pre_fn=self.model.pre_fn,
                post_fn=self.model.loss_post_fn, post_with_batch=True,
                checkpoint=cfg.checkpoint)
        else:
            raise ValueError(f"unknown schedule {cfg.schedule!r}")
        self._scheduled = cfg.schedule in ("1f1b", "interleaved-1f1b",
                                           "zb-h1")
        if self._scheduled:
            # The manual executor is training-only; eval (no grads, no remat)
            # runs an AD forward executor on the same mesh and params. The
            # executor must match the param layout: interleaved stacking
            # ([v, ...] per device) needs the interleaved executor — a plain
            # SpmdPipeline would read only group 0's slice and silently
            # evaluate d of the v*d virtual stages.
            if cfg.schedule == "interleaved-1f1b":
                from ..parallel.interleaved import InterleavedSpmdPipeline
                self.eval_pipe = InterleavedSpmdPipeline(
                    self.mesh, self.model.stage_fn, v=cfg.interleave,
                    pre_fn=self.model.pre_fn,
                    post_fn=self.model.loss_post_fn, post_with_batch=True,
                    checkpoint="never")
            else:
                self.eval_pipe = SpmdPipeline(
                    self.mesh, self.model.stage_fn, pre_fn=self.model.pre_fn,
                    post_fn=self.model.loss_post_fn, post_with_batch=True,
                    checkpoint="never")
        else:
            self.eval_pipe = dataclasses.replace(self.pipe,
                                                 checkpoint="never") \
                if cfg.checkpoint != "never" else self.pipe

        # StepLR per epoch (reference main.py:185): the per-epoch learning
        # rate is a traced argument of the jitted step, not a Python
        # closure — closures bake at trace time.
        self.tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.scale_by_adam(
                mu_dtype=jnp.dtype(cfg.mu_dtype) if cfg.mu_dtype else None),
        )
        # ZeRO-1 layout trees; populated by init_state (they need concrete
        # placed params). The jitted step traces on first call, after that.
        self._zero_shardings = None
        self._param_shardings = None
        if cfg.elastic is not None:
            if cfg.resilience is None:
                raise ValueError(
                    "TrainerConfig.elastic requires resilience= (the "
                    "elastic rung extends the guarded step's ladder)")
            if cfg.schedule in ("interleaved", "interleaved-1f1b"):
                raise ValueError(
                    "elastic training needs one stage per device "
                    f"(schedule {cfg.schedule!r} interleaves "
                    f"{cfg.interleave} virtual stages per device)")
            self._step_fn = jax.jit(self._train_step_elastic,
                                    donate_argnums=(0,))
        elif cfg.resilience is not None:
            self._step_fn = jax.jit(self._train_step_guarded,
                                    donate_argnums=(0,))
        else:
            self._step_fn = jax.jit(self._train_step, donate_argnums=(0,))
        self._eval_fn = jax.jit(self._eval_loss)
        if cfg.tb_dir is not None:
            from ..obs.tb_writer import ScalarWriter
            self.tb: Optional["ScalarWriter"] = ScalarWriter(cfg.tb_dir)
        else:
            self.tb = None
        # Telemetry sinks: the process-local registry (cheap counters the
        # executors also feed) and the structured event log. With no
        # telemetry_dir the event log is the shared null sink — call sites
        # stay unconditional, writes cost nothing.
        self.registry = get_registry()
        if cfg.telemetry_dir is not None:
            os.makedirs(cfg.telemetry_dir, exist_ok=True)
            self.events: Any = ev.EventLog(
                os.path.join(cfg.telemetry_dir, "events.jsonl"))
        else:
            self.events = ev.NULL_EVENT_LOG

    # --- state ---

    def init_state(self, key: Optional[jax.Array] = None) -> TrainState:
        key = key if key is not None else make_key(self.cfg.seed)
        sp, prep, postp = self.model.init(key)
        if self.cfg.schedule in ("interleaved", "interleaved-1f1b"):
            from ..parallel.interleaved import stack_interleaved_params
            stacked = stack_interleaved_params(sp, self.cfg.n_stages)
        else:
            stacked = stack_stage_params(sp)
        params = self._place((stacked, prep, postp))
        # tx.init's zeros_like inherits the placement; freshly-created leaves
        # (adam's count, the step counter) get replicated explicitly. Every
        # leaf then carries a mesh sharding — required both for checkpoint
        # restore (the template's shardings drive orbax) and for multi-chip.
        opt_state = self._replicate_unsharded(self.tx.init(params))
        if self.cfg.zero:
            from . import zero
            self._zero_shardings = zero.moment_shardings(
                self.mesh, params, opt_state)
            self._param_shardings = jax.tree_util.tree_map(
                lambda a: a.sharding, params)
            opt_state = zero.shard_moments(opt_state, self._zero_shardings)
        step = self._replicate_unsharded(jnp.zeros((), jnp.int32))
        return TrainState(params=params, opt_state=opt_state, step=step)

    def _replicate_unsharded(self, tree):
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())

        def fix(a):
            if isinstance(a, jax.Array) and not isinstance(a.sharding,
                                                           NamedSharding):
                return jax.device_put(a, repl)
            return a

        return jax.tree_util.tree_map(fix, tree)

    def _place(self, params):
        """Commit params to their mesh shardings (stage-stacked / replicated)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import STAGE_AXIS

        sp, prep, postp = params
        staged = NamedSharding(self.mesh, P(STAGE_AXIS))
        repl = NamedSharding(self.mesh, P())
        sp = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, staged), sp)
        prep, postp = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, repl), (prep, postp))
        return (sp, prep, postp)

    def num_params(self, state: TrainState) -> int:
        return sum(int(a.size) for a in jax.tree_util.tree_leaves(
            state.params))

    def install_autosave(self, directory: str,
                         signals: Optional[List[int]] = None) -> None:
        """Preemption-aware checkpointing: on SIGTERM (the signal cloud
        schedulers send before reclaiming a TPU VM), finish the in-flight
        step, save via :meth:`save`, and stop the epoch loop cleanly.

        The reference has no elastic story at all (SURVEY §5: "multi-host
        failure = job restart from checkpoint"); this supplies the half
        that makes restarts cheap — the checkpoint exists when the
        preemption lands, resume via ``init_state`` + ``restore_checkpoint``.
        The handler only sets a flag: all saving happens on the training
        thread between steps (signal-safe by construction).
        """
        import signal as _signal

        self._autosave_dir = directory
        self._stop_requested = False

        def _handler(signum, frame):
            self._stop_requested = True

        for sig in (signals if signals is not None
                    else [_signal.SIGTERM]):
            _signal.signal(sig, _handler)

    def _autosave_pending(self) -> bool:
        return bool(getattr(self, "_stop_requested", False))

    def _autosave(self, state: TrainState,
                  log_fn: Callable[[str], None]) -> None:
        self.save(self._autosave_dir, state)
        log_fn(f"| autosave: step {int(state.step)} checkpointed to "
               f"{self._autosave_dir} (stop requested)")

    def generate(self, state: TrainState, prompt, *,
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: Optional[int] = None, num_beams: int = 1,
                 key: Optional[jax.Array] = None):
        """Sample continuations from the trained weights — the train-state
        params (stage-stacked, mesh-placed) unstack straight into the
        KV-cached generator; no conversion, no checkpoint round-trip."""
        from ..inference import GenerationConfig, Generator

        sp, pre, post = jax.tree_util.tree_map(np.asarray, state.params)
        if self.cfg.schedule in ("interleaved", "interleaved-1f1b"):
            from ..parallel.interleaved import unstack_interleaved_params
            per_stage = unstack_interleaved_params(sp, self.cfg.n_stages)
        else:
            from ..parallel.spmd import unstack_stage_params
            per_stage = unstack_stage_params(sp, self.n_virtual)
        gen = Generator(self.model,
                        GenerationConfig(max_new_tokens=max_new_tokens,
                                         temperature=temperature,
                                         top_k=top_k, num_beams=num_beams))
        # the generator flattens blocks itself; hand it one "stage" per
        # virtual stage in true layer order
        return gen.generate((per_stage, pre, post), prompt, key=key)

    def save(self, directory: str, state: TrainState,
             step: Optional[int] = None) -> None:
        """Checkpoint with the stage-stack layout recorded (so serving can
        reconstruct layer order; interleaved schedules stack the virtual
        stages device-major-permuted)."""
        from .state import save_checkpoint

        cfg = self.cfg
        interleaved = cfg.schedule in ("interleaved", "interleaved-1f1b")
        layout = {
            "stacking": "interleaved" if interleaved else "stage",
            "n_stages": cfg.n_stages,
            "interleave": cfg.interleave if interleaved else 1,
        }
        save_checkpoint(directory, state,
                        int(state.step) if step is None else step,
                        layout=layout)

    def analytic_bubble(self) -> float:
        cfg = self.cfg
        if cfg.schedule == "interleaved":
            from ..core.schedule import InterleavedSchedule
            return InterleavedSchedule(v=cfg.interleave).device_bubble(
                cfg.chunks, cfg.n_stages)
        if cfg.schedule == "interleaved-1f1b":
            from ..core.schedule import InterleavedOneFOneBSchedule
            return InterleavedOneFOneBSchedule(
                interleave=cfg.interleave).bubble(cfg.chunks, cfg.n_stages)
        return bubble_fraction(cfg.chunks, cfg.n_stages)

    # --- steps ---

    def _loss(self, params, x, w, key, train):
        """Row-masked mean loss: ``w`` zeroes the rows ``stack_scatter``
        zero-padded for non-divisible batches, so fake rows never contaminate
        loss or gradients (VERDICT r1 #7)."""
        sp, prep, postp = params
        if train and self._scheduled:
            # The manual executor has no forward-only path; its loss comes
            # with grads attached (the hot path, _train_step, uses both).
            loss, _ = self.pipe.loss_and_grad(sp, prep, postp, x, w, key=key)
            return loss
        pipe = self.pipe if train else self.eval_pipe
        per_row = pipe(sp, prep, postp, x, key=key, train=train)
        return jnp.sum(per_row * w) / jnp.sum(w)

    def _compute_update(self, state: TrainState, x, w, key, lr,
                        inject=None, magnitude=None):
        """Shared step body: loss+grads, optional fault injection,
        optimizer update. Returns ``(params, opt_state, loss, grads)``;
        with ``inject=None`` (the unguarded step) it traces the exact
        pre-resilience program."""
        if self._scheduled:
            sp, prep, postp = state.params
            loss, grads = self.pipe.loss_and_grad(sp, prep, postp, x, w,
                                                  key=key)
        else:
            loss, grads = jax.value_and_grad(self._loss)(
                state.params, x, w, key, True)
        if inject is not None:
            from ..resilience.chaos import apply_train_faults
            loss, grads = apply_train_faults(inject, magnitude, loss, grads)
        updates, opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
        updates = jax.tree_util.tree_map(lambda u: -lr * u, updates)
        params = optax.apply_updates(state.params, updates)
        if self.cfg.zero:
            # ZeRO-1 layout pins: new moments stay data-sharded (XLA then
            # partitions the Adam update over the data axis), new params
            # return to their data-replicated placement (XLA inserts the
            # ZeRO all-gather here).
            from . import zero
            if self._zero_shardings is None:
                raise RuntimeError(
                    "TrainerConfig(zero=True) requires init_state() to run "
                    "before the first step (it derives the ZeRO layout from "
                    "the placed params)")
            opt_state = zero.constrain_moments(opt_state,
                                               self._zero_shardings)
            params = jax.tree_util.tree_map(
                lambda a, s: jax.lax.with_sharding_constraint(a, s),
                params, self._param_shardings)
        return params, opt_state, loss, grads

    def _train_step(self, state: TrainState, x, w, key, lr):
        params, opt_state, loss, _ = self._compute_update(state, x, w,
                                                          key, lr)
        return TrainState(params=params, opt_state=opt_state,
                          step=state.step + 1), loss

    def _train_step_guarded(self, state: TrainState, aux, x, w, key, lr,
                            inject, magnitude):
        """The resilient step: same update as :meth:`_train_step` plus
        (a) chaos injection selected by the traced ``inject`` code and
        (b) the fused anomaly check whose verdict ``where``-selects the
        pre-step params/opt_state back in on a bad step (skip-step — the
        step counter still advances, so the LR/PRNG walk is unaffected).
        ``aux`` carries ``(loss EWMA, consecutive anomalies, total
        anomalies)`` on device; the host reads it on its own cadence
        (``ResilienceConfig.check_every``) — no extra sync here."""
        from ..resilience.chaos import inject_scope
        from ..resilience.detect import step_guard

        rc = self.cfg.resilience
        ewma, consec, total = aux
        with inject_scope(inject):
            params, opt_state, loss, grads = self._compute_update(
                state, x, w, key, lr, inject=inject, magnitude=magnitude)
        ok, new_ewma = step_guard(
            loss, grads, ewma, state.step, spike_factor=rc.spike_factor,
            warmup_steps=rc.warmup_steps, ewma_alpha=rc.ewma_alpha)

        def select(new, old):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), new, old)

        params = select(params, state.params)
        opt_state = select(opt_state, state.opt_state)
        bad = (~ok).astype(jnp.int32)
        new_aux = (new_ewma, jnp.where(ok, jnp.int32(0), consec + 1),
                   total + bad)
        return TrainState(params=params, opt_state=opt_state,
                          step=state.step + 1), loss, new_aux

    def _train_step_elastic(self, state: TrainState, aux, x, w, key, lr,
                            inject, magnitude, kill):
        """The elastic step: the guarded step plus (a) a traced ``kill``
        code (a stage index, or KILL_NONE) that zeroes the killed
        stage's output through the wrapped stage fn, and (b) a
        per-stage gradient heartbeat appended to the aux carry — a
        ``[n_stages]`` int32 silent-streak vector the elastic
        controller reads on its host cadence. Killing stage ``j``
        silences grads for every stage ``<= j`` (the zero scale
        annihilates the backward signal), so the controller localizes
        the kill as the largest persistently-silent index. Streaks fold
        only guard-accepted steps: a NaN/spike step must escalate
        through the numeric ladder, never masquerade as a dead stage."""
        from ..resilience.chaos import inject_scope, kill_scope
        from ..resilience.detect import stage_heartbeat, step_guard

        rc = self.cfg.resilience
        ewma, consec, total, hb = aux
        with inject_scope(inject), kill_scope(kill):
            params, opt_state, loss, grads = self._compute_update(
                state, x, w, key, lr, inject=inject, magnitude=magnitude)
        ok, new_ewma = step_guard(
            loss, grads, ewma, state.step, spike_factor=rc.spike_factor,
            warmup_steps=rc.warmup_steps, ewma_alpha=rc.ewma_alpha)
        beat = stage_heartbeat(grads[0], self.n_virtual)
        silent = beat == jnp.float32(0.0)
        new_hb = jnp.where(ok, jnp.where(silent, hb + 1, jnp.int32(0)), hb)

        def select(new, old):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), new, old)

        params = select(params, state.params)
        opt_state = select(opt_state, state.opt_state)
        bad = (~ok).astype(jnp.int32)
        new_aux = (new_ewma, jnp.where(ok, jnp.int32(0), consec + 1),
                   total + bad, new_hb)
        return TrainState(params=params, opt_state=opt_state,
                          step=state.step + 1), loss, new_aux

    def elastic_store(self):
        """The trainer's buddy-replication store, created on first use.
        Lives on the Trainer (not the epoch) so the snapshot survives
        the ``StageLost`` raise and ``replan_after_loss`` can restore
        from it."""
        if getattr(self, "_buddy_store", None) is None:
            from ..resilience.elastic import BuddyStore
            self._buddy_store = BuddyStore(
                self.mesh, self.cfg.n_stages,
                verify=getattr(self.cfg.elastic, "verify_replication", True),
                registry=self.registry, events=self.events,
                snapshot_dir=getattr(self.cfg.elastic, "snapshot_dir", None))
        return self._buddy_store

    def _eval_loss(self, params, x, w):
        return self._loss(params, x, w, make_key(0), False)

    # --- data plumbing ---

    def _make_x(self, data: np.ndarray, target: np.ndarray):
        """Stack-scatter the batch; return it with the valid-row mask."""
        x = {"tokens": jnp.asarray(data), "targets": jnp.asarray(target)}
        stacked, n_rows = mb.stack_scatter(x, self.cfg.chunks)
        return stacked, mb.valid_row_mask(stacked, n_rows)

    def _batches(self, source: np.ndarray, n: int, start: int = 0):
        """Yield full (data, target) batches ``start`` .. ``n``-1.

        With ``prefetch_depth > 0`` (and a toolchain), assembly runs on the
        native producer thread; the yielded slot views are copied before
        handing out because jax CPU arrays may alias aligned host numpy
        buffers, and a slot may be overwritten as soon as the iterator
        advances past it — a small memcpy, the transpose gather stays off
        the hot loop.
        Otherwise: inline ``get_batch`` (the reference's walk), stopping at
        the first short tail batch to keep shapes static.

        ``start`` skips the first batches — the resume hook for
        :class:`~..resilience.RetryingIterator`, which rebuilds a failed
        iterator at its position.
        """
        cfg = self.cfg
        if cfg.prefetch_depth > 0:
            from ..data.native import BatchPrefetcher, prefetch_available
            if prefetch_available():
                with BatchPrefetcher(source, cfg.bptt,
                                     depth=cfg.prefetch_depth) as pf:
                    for i, (d, t) in enumerate(pf):
                        if i >= n:
                            break
                        if i < start:
                            continue
                        yield d.copy(), t.copy()
                return
        for b in range(start, n):
            data, target = lm_text.get_batch(source, b * cfg.bptt, cfg.bptt)
            if data.shape[1] < cfg.bptt:  # tail batch: keep shapes static
                return
            yield data, target

    # --- epochs ---

    def train_epoch(self, source: np.ndarray, epoch: int = 0,
                    state: Optional[TrainState] = None,
                    max_steps: Optional[int] = None,
                    log_every: int = 10,
                    log_fn: Callable[[str], None] = print,
                    start_step: int = 0):
        """One pass over ``source`` (a ``batchify``'d id matrix).

        ``start_step`` resumes the epoch mid-pass at a global batch
        index (the elastic recovery hook): batches, per-step PRNG folds
        and chaos indices all replay from the GLOBAL index, so a run
        rewound to step ``s`` and resumed with ``start_step=s`` walks
        the identical tape an uninterrupted run would.
        """
        cfg = self.cfg
        state = state if state is not None else self.init_state()
        lr = cfg.lr * cfg.lr_gamma ** epoch  # StepLR, main.py:185
        n = lm_text.num_batches(source, cfg.bptt)
        if max_steps is not None:
            n = min(n, max_steps)
        key = jax.random.fold_in(make_key(cfg.seed), epoch)

        tokens_per_step = cfg.batch_size * cfg.bptt
        # Per-step telemetry: registry instruments are live regardless (a
        # disabled registry hands back no-ops); StepReports and spans go to
        # the JSONL event log only when telemetry_dir is configured.
        telemetry_on = self.events is not ev.NULL_EVENT_LOG
        step_timer = self.registry.timer("train.step_sec")
        steps_ctr = self.registry.counter("train.steps")
        tokens_ctr = self.registry.counter("train.tokens")
        tps_gauge = self.registry.gauge("train.tokens_per_sec")
        peak = peak_flops_per_chip() if telemetry_on else None
        device_kind = jax.devices()[0].device_kind if telemetry_on else None

        # Resilience plumbing — all of it gated on cfg.resilience so the
        # default loop touches none of these objects.
        rc = cfg.resilience
        resil = None
        elastic = None
        aux = None
        if rc is not None:
            from ..resilience.recover import (ResilienceController,
                                              RetryingIterator)
            resil = ResilienceController(rc, self.registry, self.events,
                                         log_fn=log_fn)
            aux = (jnp.float32(0.0), jnp.int32(0), jnp.int32(0))
            if cfg.elastic is not None:
                from ..resilience.elastic import ElasticController
                elastic = ElasticController(
                    cfg.elastic, self.elastic_store(),
                    registry=self.registry, events=self.events,
                    log_fn=log_fn)
                aux = aux + (jnp.zeros((self.n_virtual,), jnp.int32),)
            batch_iter = RetryingIterator(
                lambda pos: self._batches(source, n, start=pos),
                retries=rc.data_retries, backoff_s=rc.data_backoff_s,
                chaos=self.chaos, registry=self.registry,
                events=self.events, start=start_step)
        else:
            batch_iter = self._batches(source, n, start=start_step)

        t_first = t0 = time.perf_counter()
        losses = []
        w = None
        for i, (data, target) in enumerate(batch_iter):
            # b is the GLOBAL batch index (data position, PRNG fold,
            # chaos index); i counts this call's iterations (compile
            # sync, steady-state timing).
            b = start_step + i
            x, mask = self._make_x(data, target)
            # Row count is constant until the tail-batch break, so the valid-
            # row mask is too — build it once, not per step.
            w = mask if w is None else w
            tracing = bool(telemetry_on and cfg.profile_every
                           and (b + 1) % cfg.profile_every == 0)
            t_step = time.perf_counter()
            with contextlib.ExitStack() as scopes:
                scopes.enter_context(self.events.span(ev.STEP, step=b,
                                                      epoch=epoch))
                if tracing:
                    trace_dir = os.path.join(cfg.telemetry_dir,
                                             f"trace_step{b + 1}")
                    scopes.enter_context(profile_trace(trace_dir))
                if elastic is not None:
                    inject, mag = (self.chaos.train_inject(b)
                                   if self.chaos is not None else (0, 1.0))
                    from ..resilience.chaos import KILL_NONE
                    kill = (self.chaos.train_kill(b)
                            if self.chaos is not None else KILL_NONE)
                    state, loss, aux = self._step_fn(
                        state, aux, x, w, jax.random.fold_in(key, b),
                        jnp.float32(lr), jnp.int32(inject),
                        jnp.float32(mag), jnp.int32(kill))
                elif rc is not None:
                    inject, mag = (self.chaos.train_inject(b)
                                   if self.chaos is not None else (0, 1.0))
                    state, loss, aux = self._step_fn(
                        state, aux, x, w, jax.random.fold_in(key, b),
                        jnp.float32(lr), jnp.int32(inject),
                        jnp.float32(mag))
                else:
                    state, loss = self._step_fn(state, x, w,
                                                jax.random.fold_in(key, b),
                                                jnp.float32(lr))
                # Virtual-CPU platform: serialize steps (see
                # sync_if_forced_cpu — interleaved async runs livelock the
                # collective rendezvous there). No-op on real TPU.
                sync_if_forced_cpu(loss)
                if tracing:
                    jax.block_until_ready(loss)  # capture the whole step
            wall = time.perf_counter() - t_step
            step_timer.observe(wall)
            steps_ctr.inc()
            tokens_ctr.inc(tokens_per_step)
            if wall > 0:
                tps_gauge.set(tokens_per_step / wall)
            losses.append(loss)
            at_log = bool(log_every and (b + 1) % log_every == 0)
            if telemetry_on:
                # Caveat: on async-dispatch backends per-step wall time is
                # honest only at sync points (forced-CPU syncs every step;
                # elsewhere log/trace steps sync). compile_inclusive marks
                # the step-0 outlier.
                if tracing:
                    self.events.event("profile_trace", step=b,
                                      path=trace_dir)
                report = StepReport.compute(
                    step=int(state.step), wall_sec=wall,
                    tokens=tokens_per_step, n_stages=cfg.n_stages,
                    chunks=cfg.chunks, checkpoint=cfg.checkpoint,
                    schedule=cfg.schedule,
                    loss=float(loss) if at_log else None,
                    model_cfg=self.model_cfg,
                    analytic_bubble=self.analytic_bubble(),
                    memory=(device_memory_peaks()
                            if at_log or i == 0 else {}),
                    compile_inclusive=(i == 0), peak_flops=peak,
                    platform=jax.default_backend(),
                    device_kind=device_kind, epoch=epoch)
                self.events.step_report(report)
                if self.tb is not None and at_log:
                    for tag, val in report.scalar_items():
                        self.tb.add_scalar(tag, val, int(state.step))
            if resil is not None:
                # Rewind/abort policy on the host cadence; may replace
                # (state, aux) with known-good copies or raise
                # TrainingAborted after the rewind budget. The elastic
                # heartbeat streak rides outside the numeric triple —
                # it survives a numeric rewind untouched.
                if elastic is not None:
                    state, aux3 = resil.after_step(b, state, aux[:3])
                    aux = aux3 + (aux[3],)
                    # Buddy capture on healthy cadence; raises StageLost
                    # once a stage's silent streak crosses dead_after.
                    state, aux = elastic.after_step(b, state, aux)
                else:
                    state, aux = resil.after_step(b, state, aux)
            if self._autosave_pending():
                self._autosave(state, log_fn)
                break
            if i == 0:
                float(loss)               # sync out the compile
                t0 = time.perf_counter()  # steady-state timing from step 2
            if at_log:
                l = float(losses[-1])
                # Steady-state ms/batch from step 2 on; the step-1 line has no
                # steady-state sample yet, so it reports the compile-inclusive
                # first-step time instead of a meaningless ~0.
                dt = ((time.perf_counter() - t0) / i if i >= 1
                      else time.perf_counter() - t_first)
                log_fn(f"| epoch {epoch} | step {b+1}/{n} "
                       f"| lr {lr:.3f} "
                       f"| ms/batch {dt*1000:.1f} "
                       f"| tok/s {tokens_per_step/dt:,.0f} "
                       f"| loss {l:.3f} | ppl {np.exp(min(l, 20.0)):.2f} "
                       f"| bubble {self.analytic_bubble():.1%}")
                if self.tb is not None:
                    gstep = int(state.step)
                    self.tb.add_scalar("train/loss", l, gstep)
                    self.tb.add_scalar("train/ppl",
                                       float(np.exp(min(l, 20.0))), gstep)
                    self.tb.add_scalar("train/tok_s",
                                       tokens_per_step / dt, gstep)
                    self.tb.add_scalar("train/ms_batch", dt * 1000, gstep)
                    self.tb.add_scalar("train/lr", lr, gstep)
                    self.tb.add_scalar("pipeline/bubble",
                                       self.analytic_bubble(), gstep)
                    self.tb.flush()  # visible live; crash loses nothing
        final = float(losses[-1]) if losses else float("nan")
        if self.tb is not None and losses:
            self.tb.add_scalar("train/epoch_loss", final, int(state.step))
            self.tb.flush()
        if telemetry_on:
            self.events.metrics_snapshot(self.registry)
            self.events.flush()
        # t0 was reset after step 0, so elapsed covers len(losses)-1 steps
        info = {"loss": final,
                "steps": len(losses),
                "sec_per_step": (time.perf_counter() - t0)
                / max(len(losses) - 1, 1)}
        if resil is not None:
            info["anomalies"] = resil.anomalies
            info["rewinds"] = resil.rewinds
            info["loss_ewma"] = float(aux[0])
        if elastic is not None:
            info["buddy_snapshots"] = elastic.snapshots
            # per-step loss series keyed by GLOBAL batch index, so a
            # resumed segment's trajectory can be compared against an
            # uninterrupted run's (tests + tools/elastic_bench.py)
            info["loss_by_step"] = {start_step + i: float(l)
                                    for i, l in enumerate(losses)}
        return state, info

    def evaluate(self, source: np.ndarray, state: TrainState,
                 max_steps: Optional[int] = None) -> float:
        """Mean eval loss over ``source`` (reference ``evaluate``,
        ``main.py:275-289``, there commented out). Logged to
        ``eval/loss`` when a TB writer is configured."""
        cfg = self.cfg
        n = lm_text.num_batches(source, cfg.bptt)
        if max_steps is not None:
            n = min(n, max_steps)
        total, count = 0.0, 0
        w = None
        for b in range(n):
            data, target = lm_text.get_batch(source, b * cfg.bptt, cfg.bptt)
            if data.shape[1] < cfg.bptt:
                break
            x, mask = self._make_x(data, target)
            w = mask if w is None else w
            loss = self._eval_fn(state.params, x, w)
            total += float(loss) * data.size
            count += data.size
        mean = total / max(count, 1)
        if self.tb is not None and count:
            self.tb.add_scalar("eval/loss", mean, int(state.step))
            self.tb.flush()
        return mean
