"""Training: jitted pipelined train step, state, checkpoint/resume."""

from .loop import Trainer, TrainerConfig
from .state import (TrainState, latest_step, restore_checkpoint,
                    save_checkpoint)

__all__ = [
    "Trainer", "TrainerConfig", "TrainState",
    "save_checkpoint", "restore_checkpoint", "latest_step",
]
