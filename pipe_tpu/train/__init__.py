"""Training: jitted pipelined train step, state, checkpoint/resume."""

from .loop import Trainer, TrainerConfig
from .state import (TrainState, latest_step, restore_checkpoint,
                    save_checkpoint)
from .zero import moment_shardings, shard_moments, zero_report

__all__ = [
    "Trainer", "TrainerConfig", "TrainState",
    "save_checkpoint", "restore_checkpoint", "latest_step",
    "moment_shardings", "shard_moments", "zero_report",
]
