"""Train state + model-state checkpointing (save/resume).

The reference has NO model-state checkpointing — "checkpoint" there means
activation rematerialization only; nothing saves or restores weights
(SURVEY §5 "Checkpoint / resume"). This module supplies that missing
capability the TPU-native way: an immutable :class:`TrainState` pytree and
Orbax-backed, sharding-aware save/restore (works for both the serial Pipe
params and the stacked SPMD params).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax

__all__ = ["TrainState", "save_checkpoint", "restore_checkpoint",
           "latest_step", "checkpoint_params_layout", "restore_params",
           "read_params_layout"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """One pytree holding everything a resumable step needs."""

    params: Any
    opt_state: Any
    step: jax.Array  # scalar int32


def _manager(directory: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                             create=True))


def save_checkpoint(directory: str, state: TrainState, step: int,
                    max_to_keep: int = 3,
                    layout: Optional[dict] = None) -> None:
    """Write an atomic, sharding-aware checkpoint for ``step``.

    ``layout`` (optional) records how ``state.params``' stage stack was
    built — ``{"stacking": "stage"|"interleaved", "n_stages": d,
    "interleave": v}`` — in ``params_layout.json`` next to the steps, so
    serving consumers (``apps/generate.py``) can reconstruct the true layer
    order (interleaved stacking permutes rows device-major;
    ``parallel/interleaved.py``). ``Trainer.save`` passes it automatically.
    """
    import orbax.checkpoint as ocp

    with _manager(directory, max_to_keep) as mngr:
        mngr.save(step, args=ocp.args.StandardSave(state))
        mngr.wait_until_finished()
    # One writer only (multi-host saves run on every process against the
    # same dir), through the same path abstraction orbax uses (so gs://
    # and friends work).
    if jax.process_index() != 0:
        return
    import json

    from etils import epath

    record = epath.Path(directory) / "params_layout.json"
    if layout is not None:
        record.write_text(json.dumps(layout))
    else:
        # a layout-less save into a dir that has a record: the record may
        # describe a DIFFERENT stacking — stale info is worse than none
        record.unlink(missing_ok=True)


def read_params_layout(directory: str) -> Optional[dict]:
    """The ``layout`` dict recorded at save time, or None (unknown —
    assume plain stage-major stacking)."""
    import json

    from etils import epath

    record = epath.Path(directory) / "params_layout.json"
    if not record.exists():
        return None
    return json.loads(record.read_text())


def restore_checkpoint(directory: str, template: TrainState,
                       step: Optional[int] = None) -> TrainState:
    """Restore ``step`` (default: latest) into ``template``'s structure.

    ``template`` supplies shapes/dtypes/shardings — pass a freshly-built
    TrainState (e.g. from ``init``) so restoration reproduces its layout.
    """
    import orbax.checkpoint as ocp

    with _manager(directory) as mngr:
        if step is None:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
        return mngr.restore(step, args=ocp.args.StandardRestore(template))


def latest_step(directory: str) -> Optional[int]:
    with _manager(directory) as mngr:
        return mngr.latest_step()


def checkpoint_params_layout(directory: str,
                             step: Optional[int] = None):
    """Read the SAVED stage layout from checkpoint metadata (no restore).

    Returns ``(n_stages, blocks_per_stage)`` for a Trainer-saved state
    (stage-stacked params: a list of ``blocks_per_stage`` block pytrees
    whose leaves lead with the ``n_stages`` axis).
    """
    import pathlib

    import orbax.checkpoint as ocp

    with _manager(directory) as mngr:
        if step is None:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
        path = pathlib.Path(mngr.directory) / str(step) / "default"
    md = ocp.StandardCheckpointHandler().metadata(path).tree
    stacked = md["params"][0]
    lps = len(stacked)
    leaf = jax.tree_util.tree_leaves(stacked[0])[0]
    return int(leaf.shape[0]), lps


def restore_params(directory: str, params_template: Any,
                   step: Optional[int] = None) -> Any:
    """Restore ONLY the ``params`` subtree of a saved :class:`TrainState`.

    For consumers that don't know (or want) the optimizer state — e.g. the
    generation driver serving a training checkpoint. ``params_template``
    must match the layout the state was SAVED in (the Trainer saves
    stage-STACKED params; see ``parallel.spmd.stack_stage_params``).
    """
    import orbax.checkpoint as ocp

    with _manager(directory) as mngr:
        if step is None:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
        restored = mngr.restore(
            step,
            args=ocp.args.PyTreeRestore(item={"params": params_template},
                                        partial_restore=True))
        return restored["params"]
