"""Train state + model-state checkpointing (save/resume).

The reference has NO model-state checkpointing — "checkpoint" there means
activation rematerialization only; nothing saves or restores weights
(SURVEY §5 "Checkpoint / resume"). This module supplies that missing
capability the TPU-native way: an immutable :class:`TrainState` pytree and
Orbax-backed, sharding-aware save/restore (works for both the serial Pipe
params and the stacked SPMD params).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax

__all__ = ["TrainState", "CheckpointCorrupt", "save_checkpoint",
           "restore_checkpoint", "latest_step", "checkpoint_params_layout",
           "restore_params", "read_params_layout", "state_manifest",
           "stage_shard_manifest", "write_buddy_manifest",
           "read_buddy_manifest"]


class CheckpointCorrupt(RuntimeError):
    """A restored checkpoint's content hash disagrees with the manifest
    recorded at save time. The message names the first corrupt leaf —
    restore refuses to hand back silently-wrong weights."""


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """One pytree holding everything a resumable step needs."""

    params: Any
    opt_state: Any
    step: jax.Array  # scalar int32


def state_manifest(state: Any) -> dict:
    """Per-leaf sha256 content hashes of a state pytree, keyed by tree
    path (``jax.tree_util.keystr``). The hash covers dtype, shape and the
    raw bytes, so any bit flip — in value, shape or dtype — changes it."""
    import hashlib

    import numpy as np

    leaves = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    for path, leaf in flat:
        name = jax.tree_util.keystr(path)
        h = hashlib.sha256()
        try:
            arr = np.asarray(leaf)
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        except (TypeError, ValueError):
            h.update(repr(leaf).encode())
        leaves[name] = h.hexdigest()
    return leaves


def stage_shard_manifest(staged_leaves: Any, n_stages: int) -> dict:
    """Per-STAGE sha256 hashes of a stage-stacked pytree (every leaf
    leads with the ``n_stages`` axis) — the buddy-replication pin. Each
    stage's digest covers the dtype, shape and raw bytes of that
    stage's slice of every leaf in flattening order, so a buddy copy of
    shard ``j`` can be verified bitwise against the source shard
    without shipping the source around."""
    import hashlib

    import numpy as np

    digests = {}
    leaves = jax.tree_util.tree_leaves(staged_leaves)
    for j in range(n_stages):
        h = hashlib.sha256()
        for leaf in leaves:
            arr = np.asarray(leaf)[j]
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        digests[str(j)] = h.hexdigest()
    return digests


def _manifest_path(directory: str, step: int):
    from etils import epath

    return epath.Path(directory) / f"manifest_step{step}.json"


def _atomic_write_json(target, doc: dict) -> None:
    """Write ``doc`` to ``target`` atomically AND durably: temp name,
    fsync the data, rename, fsync the directory. A host crash at any
    point leaves either no file or a complete one — never a torn file,
    and never a rename that outlives its (unsynced) content. Non-local
    epath backends (gs:// etc.) have no fd to fsync; those fall back to
    the plain temp+rename, whose stores are already atomic."""
    import json
    import os

    payload = json.dumps(doc, indent=0, sort_keys=True)
    tmp = target.parent / f".{target.name}.tmp"
    try:
        fd = os.open(os.fspath(tmp), os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                     0o644)
        try:
            os.write(fd, payload.encode())
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(os.fspath(tmp), os.fspath(target))
        dfd = os.open(os.fspath(target.parent), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        return
    except (OSError, TypeError, ValueError):
        pass
    tmp.write_text(payload)
    try:
        tmp.rename(target)
    except OSError:
        # some epath backends lack rename; fall back to direct write
        target.write_text(tmp.read_text())
        tmp.unlink(missing_ok=True)


def _write_manifest(directory: str, step: int, manifest: dict) -> None:
    """Write the manifest atomically (see :func:`_atomic_write_json`),
    so a crash mid-write leaves either no manifest (restore skips
    verification with a warning) or a complete one — never a torn
    file."""
    _atomic_write_json(_manifest_path(directory, step),
                       {"step": step, "leaves": manifest})


def _buddy_manifest_path(directory: str, step: int):
    from etils import epath

    return epath.Path(directory) / f"buddy_step{step}.json"


def write_buddy_manifest(directory: str, step: int,
                         shards: dict, n_stages: int) -> None:
    """Persist a buddy-snapshot manifest (per-stage shard digests from
    :func:`stage_shard_manifest`) with the same fsync'd tmp+rename
    discipline as checkpoint manifests. The elastic controller writes
    one per capture when given a directory, so a post-crash operator
    can audit which buddy generation was consistent."""
    _atomic_write_json(
        _buddy_manifest_path(directory, step),
        {"step": step, "n_stages": n_stages, "stage_shards": shards})


def read_buddy_manifest(directory: str, step: int) -> Optional[dict]:
    """Read a buddy-snapshot manifest, or None when absent. Leftover
    temp files from a torn write (``.buddy_step{N}.json.tmp``) are
    never consulted — only a completed rename counts."""
    import json

    record = _buddy_manifest_path(directory, step)
    if not record.exists():
        return None
    return json.loads(record.read_text())


def _manager(directory: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                             create=True))


def save_checkpoint(directory: str, state: TrainState, step: int,
                    max_to_keep: int = 3,
                    layout: Optional[dict] = None) -> None:
    """Write an atomic, sharding-aware checkpoint for ``step``.

    ``layout`` (optional) records how ``state.params``' stage stack was
    built — ``{"stacking": "stage"|"interleaved", "n_stages": d,
    "interleave": v}`` — in ``params_layout.json`` next to the steps, so
    serving consumers (``apps/generate.py``) can reconstruct the true layer
    order (interleaved stacking permutes rows device-major;
    ``parallel/interleaved.py``). ``Trainer.save`` passes it automatically.

    Atomicity + verifiability: orbax itself commits via temp dir +
    rename (a crashed save never looks like a checkpoint), and this
    function additionally records a per-leaf sha256 manifest
    (``manifest_step{N}.json``, written tmp+rename) that
    :func:`restore_checkpoint` validates — a corrupt leaf fails loudly
    by name instead of training on garbage.
    """
    import orbax.checkpoint as ocp

    manifest = state_manifest(state)
    with _manager(directory, max_to_keep) as mngr:
        mngr.save(step, args=ocp.args.StandardSave(state))
        mngr.wait_until_finished()
    # One writer only (multi-host saves run on every process against the
    # same dir), through the same path abstraction orbax uses (so gs://
    # and friends work).
    if jax.process_index() != 0:
        return
    import json

    from etils import epath

    _write_manifest(directory, step, manifest)
    record = epath.Path(directory) / "params_layout.json"
    if layout is not None:
        record.write_text(json.dumps(layout))
    else:
        # a layout-less save into a dir that has a record: the record may
        # describe a DIFFERENT stacking — stale info is worse than none
        record.unlink(missing_ok=True)


def read_params_layout(directory: str) -> Optional[dict]:
    """The ``layout`` dict recorded at save time, or None (unknown —
    assume plain stage-major stacking)."""
    import json

    from etils import epath

    record = epath.Path(directory) / "params_layout.json"
    if not record.exists():
        return None
    return json.loads(record.read_text())


def restore_checkpoint(directory: str, template: TrainState,
                       step: Optional[int] = None,
                       verify: bool = True) -> TrainState:
    """Restore ``step`` (default: latest) into ``template``'s structure.

    ``template`` supplies shapes/dtypes/shardings — pass a freshly-built
    TrainState (e.g. from ``init``) so restoration reproduces its layout.

    With ``verify=True`` (default) the restored leaves are re-hashed
    against the manifest recorded at save time; a mismatch raises
    :class:`CheckpointCorrupt` naming the corrupt leaf. A checkpoint
    saved before manifests existed restores with a warning.
    """
    import orbax.checkpoint as ocp

    with _manager(directory) as mngr:
        if step is None:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
        restored = mngr.restore(step, args=ocp.args.StandardRestore(template))
    if verify:
        _verify_manifest(directory, step, restored)
    return restored


def _verify_manifest(directory: str, step: int, restored: Any) -> None:
    import json
    import warnings

    record = _manifest_path(directory, step)
    if not record.exists():
        warnings.warn(
            f"checkpoint step {step} in {directory} has no content "
            f"manifest (saved by an older build?) — restoring "
            f"unverified", RuntimeWarning, stacklevel=3)
        return
    saved = json.loads(record.read_text())["leaves"]
    actual = state_manifest(restored)
    for name, digest in saved.items():
        got = actual.get(name)
        if got is None:
            raise CheckpointCorrupt(
                f"checkpoint step {step} in {directory}: leaf {name} is "
                f"in the save-time manifest but missing from the "
                f"restored tree (template/layout mismatch?)")
        if got != digest:
            raise CheckpointCorrupt(
                f"checkpoint step {step} in {directory}: leaf {name} "
                f"hash mismatch (saved {digest[:16]}…, restored "
                f"{got[:16]}…) — the checkpoint is corrupt or was "
                f"restored into the wrong template")


def latest_step(directory: str) -> Optional[int]:
    with _manager(directory) as mngr:
        return mngr.latest_step()


def checkpoint_params_layout(directory: str,
                             step: Optional[int] = None):
    """Read the SAVED stage layout from checkpoint metadata (no restore).

    Returns ``(n_stages, blocks_per_stage)`` for a Trainer-saved state
    (stage-stacked params: a list of ``blocks_per_stage`` block pytrees
    whose leaves lead with the ``n_stages`` axis).
    """
    import pathlib

    import orbax.checkpoint as ocp

    with _manager(directory) as mngr:
        if step is None:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
        path = pathlib.Path(mngr.directory) / str(step) / "default"
    md = ocp.StandardCheckpointHandler().metadata(path)
    # orbax >= 0.9 wraps the metadata pytree in an object with a ``.tree``
    # attribute; 0.7.x hands back the pytree itself.
    md = getattr(md, "tree", md)
    stacked = md["params"][0]
    lps = len(stacked)
    leaf = jax.tree_util.tree_leaves(stacked[0])[0]
    return int(leaf.shape[0]), lps


def restore_params(directory: str, params_template: Any,
                   step: Optional[int] = None) -> Any:
    """Restore ONLY the ``params`` subtree of a saved :class:`TrainState`.

    For consumers that don't know (or want) the optimizer state — e.g. the
    generation driver serving a training checkpoint. ``params_template``
    must match the layout the state was SAVED in (the Trainer saves
    stage-STACKED params; see ``parallel.spmd.stack_stage_params``).
    """
    import orbax.checkpoint as ocp

    with _manager(directory) as mngr:
        if step is None:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
        try:
            restored = mngr.restore(
                step,
                args=ocp.args.PyTreeRestore(item={"params": params_template},
                                            partial_restore=True))
        except TypeError:
            # orbax < 0.9 has no ``partial_restore`` — build a full template
            # from the saved metadata (ShapeDtypeStructs for the subtrees we
            # don't care about) and slice ``params`` out of the restore.
            import pathlib

            path = pathlib.Path(mngr.directory) / str(step) / "default"
            md = ocp.StandardCheckpointHandler().metadata(path)
            md = getattr(md, "tree", md)
            full = {
                k: (params_template if k == "params" else
                    jax.tree_util.tree_map(
                        lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype), v))
                for k, v in md.items()
            }
            restored = mngr.restore(step, args=ocp.args.StandardRestore(full))
        return restored["params"]
