"""Train state + model-state checkpointing (save/resume).

The reference has NO model-state checkpointing — "checkpoint" there means
activation rematerialization only; nothing saves or restores weights
(SURVEY §5 "Checkpoint / resume"). This module supplies that missing
capability the TPU-native way: an immutable :class:`TrainState` pytree and
Orbax-backed, sharding-aware save/restore (works for both the serial Pipe
params and the stacked SPMD params).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax

__all__ = ["TrainState", "save_checkpoint", "restore_checkpoint",
           "latest_step"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """One pytree holding everything a resumable step needs."""

    params: Any
    opt_state: Any
    step: jax.Array  # scalar int32


def _manager(directory: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                             create=True))


def save_checkpoint(directory: str, state: TrainState, step: int,
                    max_to_keep: int = 3) -> None:
    """Write an atomic, sharding-aware checkpoint for ``step``."""
    import orbax.checkpoint as ocp

    with _manager(directory, max_to_keep) as mngr:
        mngr.save(step, args=ocp.args.StandardSave(state))
        mngr.wait_until_finished()


def restore_checkpoint(directory: str, template: TrainState,
                       step: Optional[int] = None) -> TrainState:
    """Restore ``step`` (default: latest) into ``template``'s structure.

    ``template`` supplies shapes/dtypes/shardings — pass a freshly-built
    TrainState (e.g. from ``init``) so restoration reproduces its layout.
    """
    import orbax.checkpoint as ocp

    with _manager(directory) as mngr:
        if step is None:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {directory}")
        return mngr.restore(step, args=ocp.args.StandardRestore(template))


def latest_step(directory: str) -> Optional[int]:
    with _manager(directory) as mngr:
        return mngr.latest_step()
