"""ZeRO-1 optimizer-state sharding over the data mesh axis.

The reference composes with ``DistributedDataParallel`` (reference
``pipe.py:290-293``), which replicates optimizer state on every data
replica — at 520M params, Adam's two float32 moments are 4.2 GB *per
replica*. ZeRO stage 1 (Rajbhandari et al., 2020) removes that redundancy:
each data replica owns ``1/n_data`` of the moments, computes the update for
its shard, and the updated parameters are re-gathered.

TPU-native mechanism — this is a *layout* change, not a new algorithm, so
it is expressed entirely through shardings and the XLA SPMD partitioner
(the scaling-book recipe: annotate, let XLA insert the collectives):

- Each moment leaf inherits its parameter's ``PartitionSpec`` (the stage
  axis already shards stage-stacked leaves) and additionally shards its
  largest free dimension over ``data``. No flattening/padding: sharding a
  real tensor dimension keeps every leaf inspectable and lets XLA pick the
  layout.
- Inside the jitted step, ``with_sharding_constraint`` pins the *new*
  moments to the same sharded layout and the updated parameters back to
  their data-replicated layout. XLA then partitions the elementwise Adam
  update over ``data`` (each replica touches only its moment shard — the
  grads it consumes are sliced for free from the replicated gradient) and
  inserts one all-gather to re-replicate the updated parameters: exactly
  ZeRO-1's shard-update/all-gather, compiled.

Adam is elementwise, so the sharded update matches the replicated one up
to float reduction order (grad-clip's global norm is the one cross-leaf
reduction; its partitioned sum can differ by ~1 ulp — asserted within
tolerance in ``tests/test_zero.py``). Leaves with no dimension divisible
by ``n_data`` stay replicated (reported by ``zero_report``); with the
transformer shapes this is only biases and scalars — the moment bytes
that matter all shard.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS

__all__ = ["moment_shardings", "shard_moments", "constrain_moments",
           "zero_report"]


def _leaf_spec(leaf: jax.Array) -> list:
    """The leaf's current PartitionSpec, padded to its rank."""
    spec: list = []
    if isinstance(getattr(leaf, "sharding", None), NamedSharding):
        spec = list(leaf.sharding.spec)
    spec += [None] * (leaf.ndim - len(spec))
    return spec


def _sharded_axes(entry) -> tuple:
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


def _moment_sharding(mesh, leaf: jax.Array,
                     data_axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for one moment leaf: param spec + ``data`` on the largest
    free dimension divisible by the data-axis size (replicated over
    ``data`` if none divides)."""
    d = mesh.shape[data_axis]
    spec = _leaf_spec(leaf)
    best, best_size = None, 0
    for i, (size, entry) in enumerate(zip(leaf.shape, spec)):
        if _sharded_axes(entry):
            continue  # already carries a mesh axis (e.g. the stage stack)
        if size % d == 0 and size > best_size:
            best, best_size = i, size
    if best is not None and d > 1:
        spec[best] = data_axis
    while spec and spec[-1] is None:
        spec.pop()
    return NamedSharding(mesh, P(*spec))


def _params_structure(params) -> Any:
    return jax.tree_util.tree_structure(params)


def _is_params_shaped(x, struct) -> bool:
    try:
        return jax.tree_util.tree_structure(x) == struct
    except Exception:
        return False


def moment_shardings(mesh, params, opt_state,
                     data_axis: str = DATA_AXIS):
    """A pytree (matching ``opt_state``) of NamedShardings.

    Params-shaped subtrees of ``opt_state`` (Adam's ``mu``/``nu``) get
    :func:`_moment_sharding` leafwise; every other array leaf (step
    counters, clip state) is replicated.
    """
    struct = _params_structure(params)
    repl = NamedSharding(mesh, P())

    def map_subtree(sub):
        if _is_params_shaped(sub, struct):
            return jax.tree_util.tree_map(
                lambda p: _moment_sharding(mesh, p, data_axis), sub)
        return jax.tree_util.tree_map(lambda _: repl, sub)

    return jax.tree_util.tree_map(
        map_subtree, opt_state,
        is_leaf=lambda x: _is_params_shaped(x, struct))


def shard_moments(opt_state, shardings):
    """Commit ``opt_state`` to the ZeRO layout (host-side, at init)."""
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, s), opt_state, shardings)


def constrain_moments(opt_state, shardings):
    """Pin the in-step opt_state to the ZeRO layout (inside jit)."""
    return jax.tree_util.tree_map(
        lambda a, s: jax.lax.with_sharding_constraint(a, s),
        opt_state, shardings)


def zero_report(opt_state, shardings, data_axis: str = DATA_AXIS
                ) -> Dict[str, Any]:
    """Accounting: total moment bytes, bytes actually sharded over
    ``data``, and the per-device share. For the memory test and for users
    verifying the layout took."""
    total = sharded = 0
    leaves = jax.tree_util.tree_leaves(opt_state)
    specs = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    for leaf, sh in zip(leaves, specs):
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        total += nbytes
        axes = [a for e in sh.spec for a in _sharded_axes(e)]
        if data_axis in axes:
            sharded += nbytes
    return {"total_bytes": total, "data_sharded_bytes": sharded,
            "replicated_bytes": total - sharded}
